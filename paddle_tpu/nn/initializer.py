"""Weight initializers (paddle.nn.initializer parity:
`python/paddle/nn/initializer/`)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng
from ..core.tensor import Tensor

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def _fans(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(int(s) for s in shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = _rng.default_generator.split()
        return (jax.random.normal(key, tuple(int(s) for s in shape),
                                  jnp.float32) * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        key = _rng.default_generator.split()
        lo = (self.a - self.mean) / self.std
        hi = (self.b - self.mean) / self.std
        z = jax.random.truncated_normal(key, lo, hi,
                                        tuple(int(s) for s in shape), jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = _rng.default_generator.split()
        return jax.random.uniform(key, tuple(int(s) for s in shape),
                                  jnp.float32, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = _rng.default_generator.split()
        return (jax.random.normal(key, tuple(int(s) for s in shape),
                                  jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        bound = self.gain * math.sqrt(6.0 / (fi + fo))
        key = _rng.default_generator.split()
        return jax.random.uniform(key, tuple(int(s) for s in shape),
                                  jnp.float32, -bound, bound).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="leaky_relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        key = _rng.default_generator.split()
        return (jax.random.normal(key, tuple(int(s) for s in shape),
                                  jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="leaky_relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        bound = gain * math.sqrt(3.0 / fi)
        key = _rng.default_generator.split()
        return jax.random.uniform(key, tuple(int(s) for s in shape),
                                  jnp.float32, -bound, bound).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value._value if isinstance(self.value, Tensor) else \
            jnp.asarray(np.asarray(self.value))
        return v.reshape(tuple(int(s) for s in shape)).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        key = _rng.default_generator.split()
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


def _resolve_initializer(init, shape, dtype, is_bias):
    if init is None:
        fan_in, _ = _fans(shape)
        bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
        return Uniform(-bound, bound) if not is_bias else Constant(0.0)
    if isinstance(init, Initializer):
        return init
    if callable(init):
        class _Wrap(Initializer):
            def __call__(self, s, d):
                r = init(s, d)
                return r._value if isinstance(r, Tensor) else r

        return _Wrap()
    raise TypeError(f"invalid initializer {init!r}")
