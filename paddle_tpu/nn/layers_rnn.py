"""Recurrent layers (paddle.nn.layer.rnn parity). Cells are exposed for
step-wise use; full-sequence layers run `lax.scan` inside one op — static
control flow XLA can pipeline, replacing the reference's cuDNN RNN kernels."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .initializer import Uniform
from .layer_base import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN", "SimpleRNN",
           "LSTM", "GRU"]


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gate_mult, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        g = gate_mult * hidden_size
        self.weight_ih = self.create_parameter([g, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([g, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([g], attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([g], attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self.activation = activation

    def forward(self, inputs, states=None):
        import paddle_tpu as P

        if states is None:
            states = P.zeros([inputs.shape[0], self.hidden_size],
                             inputs.dtype)
        act = jnp.tanh if self.activation == "tanh" else \
            (lambda v: jnp.maximum(v, 0))

        def f(x, h, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            return act(z)

        h = apply("simple_rnn_cell", f, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 4, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        import paddle_tpu as P

        if states is None:
            z = P.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
            states = (z, z.clone())
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fg = jax.nn.sigmoid(fg)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply("lstm_cell", f, inputs, h0, c0, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 3, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        import paddle_tpu as P

        if states is None:
            states = P.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (h - c) * z + c

        h = apply("gru_cell", f, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell; runs over the time axis (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as P

        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        for i in idxs:
            x_t = inputs[:, i] if t_axis == 1 else inputs[i]
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        out = P.stack(outs, axis=t_axis)
        return out, states


class _MultiLayerRNN(Layer):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        from .layers_common import LayerList

        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        n_dir = 2 if self.bidirect else 1
        self.n_dir = n_dir

        def make_cell(in_sz):
            if self.MODE == "LSTM":
                return LSTMCell(in_sz, hidden_size, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr)
            if self.MODE == "GRU":
                return GRUCell(in_sz, hidden_size, weight_ih_attr,
                               weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(in_sz, hidden_size, activation,
                                 weight_ih_attr, weight_hh_attr, bias_ih_attr,
                                 bias_hh_attr)

        cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * n_dir
            for _ in range(n_dir):
                cells.append(make_cell(in_sz))
        self.cells = LayerList(cells)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as P
        from .functional import dropout as fdropout

        x = inputs
        final_h = []
        final_c = []
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.n_dir):
                cell = self.cells[layer * self.n_dir + d]
                init = None
                if initial_states is not None:
                    if self.MODE == "LSTM":
                        h0, c0 = initial_states
                        idx = layer * self.n_dir + d
                        init = (h0[idx], c0[idx])
                    else:
                        init = initial_states[layer * self.n_dir + d]
                rnn = RNN(cell, is_reverse=(d == 1),
                          time_major=self.time_major)
                out, st = rnn(x, init)
                outs.append(out)
                if self.MODE == "LSTM":
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            x = outs[0] if len(outs) == 1 else P.concat(outs, axis=-1)
            if self.dropout and layer < self.num_layers - 1:
                x = fdropout(x, self.dropout, training=self.training)
        h = P.stack(final_h, axis=0)
        if self.MODE == "LSTM":
            c = P.stack(final_c, axis=0)
            return x, (h, c)
        return x, h


class SimpleRNN(_MultiLayerRNN):
    MODE = "RNN"


class LSTM(_MultiLayerRNN):
    MODE = "LSTM"


class GRU(_MultiLayerRNN):
    MODE = "GRU"


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (paddle.nn.BiRNN): runs
    `cell_fw` forward and `cell_bw` reversed over time, concatenating
    outputs on the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as P

        fw_init, bw_init = (initial_states
                            if initial_states is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, fw_init, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_init, sequence_length)
        return P.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


RNNCellBase = _RNNCellBase  # public alias (paddle.nn.RNNCellBase)


class BeamSearchDecoder(Layer):
    """Beam-search decoder over an RNN cell (paddle.nn.BeamSearchDecoder).

    TPU-first: the decode loop is a host loop over static-shape steps
    (each step is jit-friendly); beams are a leading batch*beam fold."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=32, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run a BeamSearchDecoder to completion (paddle.nn.dynamic_decode).
    Returns (predicted_ids [B, T, W], final_scores [B, W]) (+ lengths)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as P

    cell = decoder.cell
    W = decoder.beam_size
    # infer batch from provided initial states
    assert inits is not None, "dynamic_decode needs initial states"
    flat = inits[0] if isinstance(inits, (tuple, list)) else inits
    b = flat.shape[0]

    def tile(t):
        v = t._value if isinstance(t, Tensor) else t
        return Tensor(jnp.repeat(v, W, axis=0))

    states = jax.tree_util.tree_map(
        tile, inits, is_leaf=lambda x: isinstance(x, Tensor))
    ids = P.full([b * W], decoder.start_token, dtype="int32")
    # beam 0 active, others -inf so step 1 expands from one beam
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (W - 1), jnp.float32), b)
    finished = jnp.zeros((b * W,), bool)
    out_ids = []

    for _ in range(max_step_num):
        inp = decoder.embedding_fn(ids) if decoder.embedding_fn else \
            P.cast(ids, "float32").unsqueeze(-1)
        out, states_new = cell(inp, states)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        logp = jax.nn.log_softmax(
            logits._value.astype(jnp.float32), axis=-1)     # [B*W, V]
        v = logp.shape[-1]
        # finished beams only extend with end_token at zero cost
        end_only = jnp.full((v,), -1e9).at[decoder.end_token].set(0.0)
        logp = jnp.where(finished[:, None], end_only[None, :], logp)
        total = scores[:, None] + logp                      # [B*W, V]
        total = total.reshape(b, W * v)
        top_scores, top_idx = jax.lax.top_k(total, W)       # [B, W]
        beam_src = top_idx // v                             # which beam
        tok = (top_idx % v).astype(jnp.int32)
        gather = (jnp.arange(b)[:, None] * W + beam_src).reshape(-1)

        def regather(t):
            return Tensor(t._value[gather])

        states = jax.tree_util.tree_map(
            regather, states_new, is_leaf=lambda x: isinstance(x, Tensor))
        scores = top_scores.reshape(-1)
        finished = finished[gather] | (tok.reshape(-1) == decoder.end_token)
        ids = Tensor(tok.reshape(-1))
        # re-gather previously emitted ids so beams stay consistent
        out_ids = [o[gather] for o in out_ids]
        out_ids.append(tok.reshape(-1))
        if bool(finished.all()):
            break

    pred = jnp.stack(out_ids, axis=0).reshape(-1, b, W)     # [T, B, W]
    if not output_time_major:
        pred = jnp.moveaxis(pred, 0, 1)                     # [B, T, W]
    result = (Tensor(pred), Tensor(scores.reshape(b, W)))
    if return_length:
        steps = pred.shape[1 if not output_time_major else 0]
        lens = jnp.full((b, W), steps, jnp.int32)
        return result + (Tensor(lens),)
    return result


__all__ += ["RNNCellBase", "BeamSearchDecoder", "dynamic_decode"]
