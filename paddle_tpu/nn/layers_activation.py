"""Activation layers (paddle.nn.layer.activation parity)."""
from __future__ import annotations

from . import functional as F
from .initializer import Constant
from .layer_base import Layer

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "SELU", "CELU", "GELU",
    "Silu", "Swish", "Mish", "Hardswish", "Hardsigmoid", "Hardtanh",
    "Hardshrink", "Softshrink", "Tanhshrink", "Softsign", "Softplus",
    "Softmax", "LogSoftmax", "LogSigmoid", "Sigmoid", "Tanh", "GLU",
    "Maxout", "RReLU", "ThresholdedReLU",
]


def _simple(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            keys = list(defaults)
            for i, a in enumerate(args):
                merged[keys[i]] = a
            for k, v in kwargs.items():
                if k in merged:
                    merged[k] = v
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", lambda x: F.relu(x))
ReLU6 = _simple("ReLU6", lambda x: F.relu6(x))
LeakyReLU = _simple("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _simple("ELU", F.elu, alpha=1.0)
SELU = _simple("SELU", lambda x: F.selu(x))
CELU = _simple("CELU", F.celu, alpha=1.0)
GELU = _simple("GELU", F.gelu, approximate=False)
Silu = _simple("Silu", lambda x: F.silu(x))
Swish = _simple("Swish", lambda x: F.swish(x))
Mish = _simple("Mish", lambda x: F.mish(x))
Hardswish = _simple("Hardswish", lambda x: F.hardswish(x))
Hardsigmoid = _simple("Hardsigmoid", lambda x: F.hardsigmoid(x))
Hardtanh = _simple("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _simple("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _simple("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _simple("Tanhshrink", lambda x: F.tanhshrink(x))
Softsign = _simple("Softsign", lambda x: F.softsign(x))
Softplus = _simple("Softplus", F.softplus, beta=1, threshold=20)
Softmax = _simple("Softmax", F.softmax, axis=-1)
LogSoftmax = _simple("LogSoftmax", F.log_softmax, axis=-1)
LogSigmoid = _simple("LogSigmoid", lambda x: F.log_sigmoid(x))
Sigmoid = _simple("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _simple("Tanh", lambda x: F.tanh(x))
GLU = _simple("GLU", F.glu, axis=-1)
Maxout = _simple("Maxout", F.maxout, groups=2, axis=1)
ThresholdedReLU = _simple("ThresholdedReLU", F.thresholded_relu,
                          threshold=1.0, value=0.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, self.training)
