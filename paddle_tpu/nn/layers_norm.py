"""Normalization layers (paddle.nn.layer.norm parity:
`python/paddle/nn/layer/norm.py`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from . import functional as F
from .initializer import Constant
from .layer_base import Layer

__all__ = [
    "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
    "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
    "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    """TPU-favoured norm; parity with incubate fused_rms_norm API."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, None, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance",
                             Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW" else
                         "NHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under jit+mesh, XLA's sharding propagation
    computes global batch statistics automatically when the batch axis is
    sharded (the reference needs a dedicated sync_batch_norm kernel,
    `paddle/phi/kernels/gpu/sync_batch_norm_kernel.cu`; here data-parallel
    jit makes plain batch_norm already see the global batch when unsharded
    stats are requested via psum — eager single-process keeps local stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum,
                                layer.epsilon, data_format=layer.data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers.update(layer._buffers)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[axis]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=None)
        self.weight_v = self.create_parameter(
            [w], default_initializer=None)

    def forward(self, weight):
        import jax.numpy as jnp

        from ..core.dispatch import apply

        axis, eps, iters = self.axis, self.epsilon, self.power_iters

        def f(w, u, v):
            perm = [axis] + [i for i in range(w.ndim) if i != axis]
            mat = jnp.transpose(w, perm).reshape(w.shape[axis], -1)
            for _ in range(iters):
                v_ = mat.T @ u
                v_ = v_ / (jnp.linalg.norm(v_) + eps)
                u_ = mat @ v_
                u = u_ / (jnp.linalg.norm(u_) + eps)
                v = v_
            sigma = u @ mat @ v
            return w / sigma

        return apply("spectral_norm", f, weight, self.weight_u, self.weight_v)
