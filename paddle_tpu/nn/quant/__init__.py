"""paddle_tpu.nn.quant — weight-only / LLM.int8 quantized linear path.

Role parity: `python/paddle/nn/quant/quantized_linear.py`
(`weight_quantize:39`, `weight_dequantize:96`, `weight_only_linear:152`,
`llm_int8_linear:240`) — the serving-side quantization used for LLM
deployment. The reference lowers to cutlass int8/int4 GEMMs gated on CUDA
arch; here the contract is the same tensors in/out, with the compute
expressed as dequantize-into-matmul so XLA folds the scale multiply into
the MXU epilogue (and int8 weights halve HBM traffic — the win that
matters for memory-bound decode). No arch gate: every TPU runs it.

Layout follows the reference: quantized weight is stored TRANSPOSED
[out, in] (int8; int4 packs two signed nibbles per byte along `in`),
per-out-channel scale is [out] f32, and grouped scales are
[ceil(in/group), out].
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply, op
from ..layer_base import Layer

__all__ = ["Stub", "weight_quantize", "weight_dequantize",
           "weight_only_linear", "llm_int8_linear", "WeightOnlyLinear"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")


def _pack_int4(q):
    """q: int8 in [-8, 7], [out, in] -> [out, in//2] two nibbles/byte.
    `in` must be even — an odd width would silently drop the last column
    (or crash on the nibble merge); serving matmul dims are even in
    practice, so this is a loud precondition rather than padding the
    packed layout (which the dequant side could not distinguish from a
    real column)."""
    if q.shape[1] % 2 != 0:
        raise ValueError(
            f"weight_only_int4 requires even in_features, got {q.shape[1]}")
    lo = q[:, 0::2] & 0x0F
    hi = (q[:, 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(p):
    lo = (p.astype(jnp.int32) << 28) >> 28          # sign-extend low nibble
    hi = (p.astype(jnp.int32) << 24) >> 28          # sign-extend high nibble
    out = jnp.stack([lo, hi], axis=-1).reshape(p.shape[0], -1)
    return out.astype(jnp.int8)


@op("weight_quantize")
def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """x: [in, out] float weights. Returns (quantized [out, in] int8 —
    int4 packed to [out, in//2] — and scale: [out] f32 per-channel, or
    [in/group, out] grouped)."""
    _check(algo, group_size)
    w = jnp.asarray(x, jnp.float32)
    n_in, n_out = w.shape
    qmax = 7.0 if algo == "weight_only_int4" else 127.0
    if group_size == -1:
        scale = jnp.max(jnp.abs(w), axis=0) / qmax            # [out]
        q = jnp.round(w / jnp.maximum(scale, 1e-10)[None, :])
    else:
        g = -(-n_in // group_size)
        pad = g * group_size - n_in
        wp = jnp.pad(w, ((0, pad), (0, 0)))
        wg = wp.reshape(g, group_size, n_out)
        scale = jnp.max(jnp.abs(wg), axis=1) / qmax           # [g, out]
        q = jnp.round(wg / jnp.maximum(scale, 1e-10)[:, None, :])
        q = q.reshape(g * group_size, n_out)[:n_in]
    q = jnp.clip(q, -qmax - 1, qmax).astype(jnp.int8).T       # [out, in]
    if algo == "weight_only_int4":
        q = _pack_int4(q)
    return q, scale.astype(jnp.float32)


@op("weight_dequantize")
def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float32",
                      group_size=-1):
    """Inverse of weight_quantize: returns [in, out] floats."""
    _check(algo, group_size)
    q = jnp.asarray(x)
    if algo == "weight_only_int4":
        q = _unpack_int4(q)
    w = q.astype(jnp.float32).T                               # [in, out]
    if group_size == -1:
        w = w * jnp.asarray(scale, jnp.float32)[None, :]
    else:
        n_in, n_out = w.shape
        g = jnp.asarray(scale, jnp.float32).shape[0]
        pad = g * group_size - n_in
        wp = jnp.pad(w, ((0, pad), (0, 0))).reshape(g, group_size, n_out)
        w = (wp * jnp.asarray(scale, jnp.float32)[:, None, :]).reshape(
            g * group_size, n_out)[:n_in]
    return w.astype(out_dtype)


def _dequant_matmul(xv, qw, scale, bias, algo, group_size, out_dtype):
    w = weight_dequantize.raw(qw, scale, algo, out_dtype, group_size)
    y = jnp.matmul(xv.astype(out_dtype), w.astype(out_dtype))
    if bias is not None:
        y = y + bias.astype(out_dtype)
    return y


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x: [..., in]; weight: [out, in] int8 (or packed int4); returns
    [..., out] in x's dtype."""
    algo = "weight_only_int4" if weight_dtype == "int4" else \
        "weight_only_int8"
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")

    def f(xv, qw, scale, b):
        return _dequant_matmul(xv, qw, scale, b, algo, group_size,
                               xv.dtype)

    return apply("weight_only_linear", f, x, weight, weight_scale, bias)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8() linear (reference quantized_linear.py:240): activation
    channels whose absmax exceeds `threshold` stay in floating point
    (outlier decomposition); the rest quantize dynamically to int8 and
    multiply against the int8 weight. Static shapes: the split is a mask,
    so both partial matmuls keep the full shape (TPU-friendly — no
    data-dependent gather)."""
    def f(xv, qw, scale, b):
        out_dtype = xv.dtype
        x32 = xv.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(x32), axis=tuple(range(x32.ndim - 1)))
        outlier = absmax > threshold                          # [in]
        x_reg = jnp.where(outlier, 0.0, x32)
        x_out = jnp.where(outlier, x32, 0.0)
        # dynamic per-tensor activation scale for the regular part
        a_scale = jnp.maximum(jnp.max(jnp.abs(x_reg)), 1e-10) / 127.0
        xq = jnp.clip(jnp.round(x_reg / a_scale), -128, 127).astype(jnp.int8)
        wq = jnp.asarray(qw)                                  # [out, in]
        # int8 x int8 -> int32 accumulation on the MXU
        y_reg = jnp.matmul(xq.astype(jnp.int32), wq.T.astype(jnp.int32))
        y_reg = y_reg.astype(jnp.float32) * a_scale * \
            jnp.asarray(scale, jnp.float32)[None, :]
        w_fp = wq.astype(jnp.float32) * \
            jnp.asarray(scale, jnp.float32)[:, None]          # [out, in]
        y_out = jnp.matmul(x_out, w_fp.T)
        y = y_reg + y_out
        if b is not None:
            y = y + b.astype(jnp.float32)
        return y.astype(out_dtype)

    return apply("llm_int8_linear", f, x, weight, weight_scale, bias)


class WeightOnlyLinear(Layer):
    """Serving linear over pre-quantized weights (reference
    `paddle.nn.quant.quant_layers` role). Build one from an existing
    nn.Linear via `WeightOnlyLinear.from_linear(lin, algo)`."""

    def __init__(self, in_features, out_features, weight_dtype="int8",
                 group_size=-1, has_bias=True):
        super().__init__()
        self.weight_dtype = weight_dtype
        self.group_size = group_size
        if weight_dtype == "int4" and in_features % 2 != 0:
            raise ValueError(
                f"int4 WeightOnlyLinear requires even in_features, got "
                f"{in_features}")
        packed_in = in_features // 2 if weight_dtype == "int4" \
            else in_features
        self.quant_weight = self.create_parameter(
            [out_features, packed_in], dtype="int8",
            default_initializer=lambda *_: np.zeros(
                (out_features, packed_in), np.int8))
        if group_size == -1:
            sshape = [out_features]
        else:
            sshape = [-(-in_features // group_size), out_features]
        self.weight_scale = self.create_parameter(
            sshape, dtype="float32",
            default_initializer=lambda *_: np.ones(sshape, np.float32))
        self.bias = self.create_parameter(
            [out_features], dtype="float32", is_bias=True) \
            if has_bias else None
        for p in (self.quant_weight, self.weight_scale):
            p.stop_gradient = True

    @classmethod
    def from_linear(cls, linear, weight_dtype="int8", group_size=-1):
        algo = "weight_only_int4" if weight_dtype == "int4" else \
            "weight_only_int8"
        w = linear.weight  # [in, out]
        in_f, out_f = w.shape
        q, scale = weight_quantize(w, algo=algo, group_size=group_size)
        layer = cls(in_f, out_f, weight_dtype, group_size,
                    has_bias=linear.bias is not None)
        layer.quant_weight.set_value(q)
        layer.weight_scale.set_value(scale)
        if linear.bias is not None:
            layer.bias.set_value(linear.bias)
        return layer

    def forward(self, x):
        return weight_only_linear(
            x, self.quant_weight, self.bias, self.weight_scale,
            weight_dtype=self.weight_dtype, group_size=self.group_size)


class Stub(Layer):
    """Quantization insertion point for functional calls (reference
    `paddle/nn/quant/stub.py`): a layer's forward can't attach a quant
    config to a bare functional API, so a Stub sublayer is called on the
    functional's inputs; QAT/PTQ swap the stub for the configured quanter
    or observer. Until swapped (or with no quanter) it is identity."""

    def __init__(self, observer=None):
        super().__init__()
        # instantiate factories NOW: a lazy instantiation in forward would
        # rebuild the quanter every call (Layer.__setattr__ stores sublayers
        # in _sub_layers while the factory would keep shadowing from
        # __dict__), resetting EMA scale/calibration state each step
        if observer is not None and hasattr(observer, "instance"):
            observer = observer.instance()
        self._observer = observer

    def forward(self, x):
        if self._observer is None:
            return x
        return self._observer(x)
