"""Gradient clipping (paddle.nn.clip parity: `python/paddle/nn/clip.py`).

ClipGradByGlobalNorm is the hybrid-parallel-critical one: the distributed
optimizer subclasses extend `_global_norm` with cross-mesh-axis psum
(HybridParallelClipGrad role, `hybrid_parallel_optimizer.py:44`).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def _dygraph_clip(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, apply("clip_grad_value",
                                 lambda v: jnp.clip(v, self.min, self.max), g)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def f(v):
                norm = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
                factor = jnp.where(norm > self.clip_norm,
                                   self.clip_norm / jnp.maximum(norm, 1e-12),
                                   1.0)
                return (v.astype(jnp.float32) * factor).astype(v.dtype)

            out.append((p, apply("clip_grad_norm", f, g)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def _global_norm_sq(self, grads):
        """Sum of squares over local grads; distributed subclasses add the
        cross-axis reduction here."""
        def f(*vs):
            return sum(jnp.sum(jnp.square(v.astype(jnp.float32))) for v in vs)

        return apply("global_norm_sq", f, *grads)

    def _dygraph_clip(self, params_grads):
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads
        gsq = self._global_norm_sq(grads)

        def scale_fn(v, s):
            gn = jnp.sqrt(s)
            factor = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-6), 1.0)
            return (v.astype(jnp.float32) * factor).astype(v.dtype)

        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, apply("clip_by_global_norm", scale_fn, g, gsq)))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(0.0)

    def norm_fn(*vs):
        if norm_type == float("inf"):
            return jnp.max(jnp.stack([jnp.max(jnp.abs(v)) for v in vs]))
        return sum(jnp.sum(jnp.abs(v.astype(jnp.float32)) ** norm_type)
                   for v in vs) ** (1.0 / norm_type)

    total = apply("total_norm", norm_fn, *grads)
    clip_coef = float(max_norm) / (float(total.numpy()) + 1e-6)
    if clip_coef < 1.0:
        for p in parameters:
            if p.grad is not None:
                p.grad = Tensor(p.grad._value * clip_coef)
    return total


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad._value, -clip_value, clip_value))
