"""paddle.nn parity surface (`python/paddle/nn/`)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_,
    clip_grad_value_,
)
from .layer_base import Layer  # noqa: F401
from .layers_activation import *  # noqa: F401,F403
from .layers_common import *  # noqa: F401,F403
from .layers_conv_pool import *  # noqa: F401,F403
from .layers_loss import *  # noqa: F401,F403
from .layers_norm import *  # noqa: F401,F403
from .layers_rnn import *  # noqa: F401,F403
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .layers_transformer import *  # noqa: F401,F403
from ..core.tensor import Parameter  # noqa: F401


class ParamAttr:
    """paddle.ParamAttr parity: bundles name/initializer/lr/clip options."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
