"""nn.Layer: module base class.

Role parity: `paddle.nn.Layer` (python/paddle/nn/layer/layers.py:334) —
parameter/buffer/sublayer registries, hooks, state_dict, train/eval, to().

TPU-first addition: `functional_state` / `functional_call` — the bridge that
lets the same Layer run eagerly (params as mutable Tensors) or inside a
traced/jitted/sharded program (params as a pytree of jax arrays), which is
what jit.to_static and every parallelism recipe build on.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict

import jax
import numpy as np

from ..core import dtypes as _dtypes
from ..core.tensor import Parameter, Tensor


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._id = hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self.training = True
        self._dtype = _dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0

    # --- attribute magic -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                raise TypeError(
                    f"cannot assign non-Parameter to parameter slot {name!r}")
            if buffers is not None and name in buffers:
                buffers[name] = value if (
                    value is None or isinstance(value, Tensor)
                ) else Tensor(value)
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # --- registration --------------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        elif name in self._non_persistable_buffer_names:
            self._non_persistable_buffer_names.discard(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, _resolve_initializer

        dtype = _dtypes.convert_dtype(dtype) or self._dtype
        init = None
        name = None
        learning_rate = 1.0
        if attr is not None and attr is not False:
            init = getattr(attr, "initializer", None)
            name = getattr(attr, "name", None)
            learning_rate = getattr(attr, "learning_rate", 1.0)
        if init is None:
            init = default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else None
        init = _resolve_initializer(init, shape, dtype, is_bias)
        data = init(shape, dtype)
        p = Parameter(data, name=name)
        p.optimize_attr["learning_rate"] = learning_rate
        return p

    # --- iteration -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # --- modes ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # --- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = OrderedDict() if destination is None else destination
        for n, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            out[n] = p
        # identity-based filter: each layer owns its non-persistable set
        skip_ids = set()
        for _, layer in self.named_sublayers(include_self=True):
            for name in layer._non_persistable_buffer_names:
                b = layer._buffers.get(name)
                if b is not None:
                    skip_ids.add(id(b))
        for n, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            if id(b) not in skip_ids:
                out[n] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = v._value if isinstance(v, Tensor) else v
                val = np.asarray(val) if not hasattr(val, "dtype") else val
                if tuple(tgt._value.shape) != tuple(val.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: {tgt.shape} vs {list(val.shape)}")
                tgt.set_value(val)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # --- dtype / device ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = _dtypes.convert_dtype(dtype)
            for p in self.parameters():
                if jax.numpy.issubdtype(p._value.dtype, np.floating):
                    p._value = p._value.astype(dtype)
            for b in self.buffers():
                if jax.numpy.issubdtype(b._value.dtype, np.floating):
                    b._value = b._value.astype(dtype)
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # --- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # --- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    # --- functional bridge (TPU-native jit/shard path) -----------------------
    def functional_state(self):
        """Return (params, buffers) as flat name->jax.Array dicts."""
        params = {n: p._value for n, p in self.named_parameters()}
        buffers = {n: b._value for n, b in self.named_buffers()}
        return params, buffers

    @contextlib.contextmanager
    def bind_state(self, params=None, buffers=None):
        """Temporarily swap parameter/buffer payloads (e.g. with tracers),
        restoring (and surfacing buffer mutations) on exit."""
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved_p = {n: t._value for n, t in named_p.items()}
        saved_b = {n: t._value for n, t in named_b.items()}
        try:
            if params:
                for n, v in params.items():
                    if n in named_p:
                        named_p[n]._value = v
            if buffers:
                for n, v in buffers.items():
                    if n in named_b:
                        named_b[n]._value = v
            yield named_p, named_b
        finally:
            for n, t in named_p.items():
                t._value = saved_p[n]
            for n, t in named_b.items():
                t._value = saved_b[n]

    def functional_call(self, params, buffers, *inputs, **kwargs):
        """Pure apply: run forward with the given arrays; returns
        (outputs, new_buffers). Safe to call under jax transforms."""
        from ..core import flags

        with self.bind_state(params, buffers) as (named_p, named_b):
            with flags.trace_guard():
                wrapped = [Tensor(x, stop_gradient=True)
                           if not isinstance(x, Tensor) and hasattr(x, "shape")
                           else x for x in inputs]
                # params need stop_gradient=False so downstream logic branches
                # identically to eager
                out = self(*wrapped, **kwargs)
            new_buffers = {n: named_b[n]._value for n in named_b}

        def unwrap(o):
            return o._value if isinstance(o, Tensor) else o

        return jax.tree_util.tree_map(
            unwrap, out,
            is_leaf=lambda x: isinstance(x, Tensor)), new_buffers

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"]
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            body = "\n  ".join(rep)
            lines.append(f"  ({name}): {body}")
        return "\n".join(lines) + ")"
