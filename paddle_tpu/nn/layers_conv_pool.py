"""Conv + pooling layers (paddle.nn.layer.{conv,pooling} parity)."""
from __future__ import annotations

import numpy as np

from . import functional as F
from .initializer import KaimingUniform
from .layer_base import Layer

__all__ = [
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
]


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, transpose,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.output_padding = output_padding
        self._n = n
        if transpose:
            shape = [in_channels, out_channels // groups, *self.kernel_size]
        else:
            shape = [out_channels, in_channels // groups, *self.kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in))
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, False,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, True,
                         stride, padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, True,
                         stride, padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, True,
                         stride, padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class _PoolNd(Layer):
    def __init__(self, fn, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.fn = fn
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kw = kw

    def forward(self, x):
        return self.fn(x, self.kernel_size, self.stride, self.padding,
                       **self.kw)


class AvgPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding,
                         exclusive=exclusive, ceil_mode=ceil_mode)


class AvgPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         data_format=data_format)


class AvgPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         data_format=data_format)


class MaxPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding,
                         return_mask=return_mask, ceil_mode=ceil_mode)


class MaxPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)


class MaxPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)


class _AdaptivePoolNd(Layer):
    def __init__(self, fn, output_size, **kw):
        super().__init__()
        self.fn = fn
        self.output_size = output_size
        self.kw = kw

    def forward(self, x):
        return self.fn(x, self.output_size, **self.kw)


class AdaptiveAvgPool1D(_AdaptivePoolNd):
    def __init__(self, output_size, name=None):
        super().__init__(F.adaptive_avg_pool1d, output_size)


class AdaptiveAvgPool2D(_AdaptivePoolNd):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(F.adaptive_avg_pool2d, output_size)


class AdaptiveAvgPool3D(_AdaptivePoolNd):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(F.adaptive_avg_pool3d, output_size)


class AdaptiveMaxPool1D(_AdaptivePoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool1d, output_size)


class AdaptiveMaxPool2D(_AdaptivePoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool2d, output_size)


class AdaptiveMaxPool3D(_AdaptivePoolNd):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool3d, output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kw = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, data_format=data_format,
                       output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, **self.kw)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kw = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, data_format=data_format,
                       output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, **self.kw)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kw = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, data_format=data_format,
                       output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, **self.kw)
