"""Loss layers (paddle.nn.layer.loss parity)."""
from __future__ import annotations

from . import functional as F
from .layer_base import Layer

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "TripletMarginLoss", "SoftMarginLoss",
    "MultiLabelSoftMarginLoss", "HingeEmbeddingLoss", "PoissonNLLLoss",
]


class _LossBase(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction


class CrossEntropyLoss(_LossBase):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__(reduction)
        self.weight = weight
        self.ignore_index = ignore_index
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(_LossBase):
    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(_LossBase):
    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(_LossBase):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__(reduction)
        self.weight = weight
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__(reduction)
        self.weight = weight

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__(reduction)
        self.weight = weight
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(_LossBase):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__(reduction)
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(_LossBase):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__(reduction)
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(_LossBase):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(_LossBase):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(_LossBase):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin
        self.p = p
        self.epsilon = epsilon
        self.swap = swap

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class SoftMarginLoss(_LossBase):
    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__(reduction)
        self.weight = weight

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class HingeEmbeddingLoss(_LossBase):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class PoissonNLLLoss(_LossBase):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.log_input = log_input
        self.full = full
        self.epsilon = epsilon

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class CTCLoss(_LossBase):
    """Layer over F.ctc_loss (reference nn/layer/loss.py CTCLoss)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__(reduction)
        self.blank = blank

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(_LossBase):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean"):
        super().__init__(reduction)
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda

    def forward(self, logits, labels, input_lengths, label_lengths):
        return F.rnnt_loss(logits, labels, input_lengths, label_lengths,
                           blank=self.blank, reduction=self.reduction,
                           fastemit_lambda=self.fastemit_lambda)


class HSigmoidLoss(Layer):
    """Layer over F.hsigmoid_loss: owns the internal-node weight table."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom-tree hsigmoid is not wired (default "
                "complete-binary-tree paths only)")
        self.num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size])
        self.bias = None if bias_attr is False else \
            self.create_parameter([num_classes - 1], is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class MultiMarginLoss(_LossBase):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__(reduction)
        self.p = p
        self.margin = margin
        self.weight = weight

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p,
                                   margin=self.margin, weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(_LossBase):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class GaussianNLLLoss(_LossBase):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__(reduction)
        self.full = full
        self.epsilon = epsilon

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


__all__ += ["CTCLoss", "RNNTLoss", "HSigmoidLoss", "MultiMarginLoss",
            "TripletMarginWithDistanceLoss", "GaussianNLLLoss"]
