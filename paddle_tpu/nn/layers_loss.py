"""Loss layers (paddle.nn.layer.loss parity)."""
from __future__ import annotations

from . import functional as F
from .layer_base import Layer

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "TripletMarginLoss", "SoftMarginLoss",
    "MultiLabelSoftMarginLoss", "HingeEmbeddingLoss", "PoissonNLLLoss",
]


class _LossBase(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction


class CrossEntropyLoss(_LossBase):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__(reduction)
        self.weight = weight
        self.ignore_index = ignore_index
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(_LossBase):
    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(_LossBase):
    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(_LossBase):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__(reduction)
        self.weight = weight
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__(reduction)
        self.weight = weight

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__(reduction)
        self.weight = weight
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(_LossBase):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__(reduction)
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(_LossBase):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__(reduction)
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(_LossBase):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(_LossBase):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(_LossBase):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin
        self.p = p
        self.epsilon = epsilon
        self.swap = swap

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class SoftMarginLoss(_LossBase):
    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__(reduction)
        self.weight = weight

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class HingeEmbeddingLoss(_LossBase):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__(reduction)
        self.margin = margin

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class PoissonNLLLoss(_LossBase):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.log_input = log_input
        self.full = full
        self.epsilon = epsilon

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)
