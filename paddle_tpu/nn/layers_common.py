"""Common layers (paddle.nn common/container parity:
`python/paddle/nn/layer/{common,container}.py`)."""
from __future__ import annotations

from collections import OrderedDict

from ..core.tensor import Parameter
from . import functional as F
from .initializer import Normal, XavierUniform
from .layer_base import Layer

__all__ = [
    "PairwiseDistance", "Softmax2D", "Unflatten",
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Flatten", "Identity", "Sequential", "LayerList", "ParameterList",
    "LayerDict", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "CosineSimilarity", "Bilinear",
    "PixelShuffle", "PixelUnshuffle", "ChannelShuffle", "Unfold", "Fold",
]


class Linear(Layer):
    """`paddle.nn.Linear` (weight stored [in_features, out_features])."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:

            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx % len(self) if idx < 0 else idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        return self._sub_layers.pop(key)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding if isinstance(self.padding, (list, tuple))
                     else [self.padding] * 2, self.mode, self.value,
                     self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PairwiseDistance(Layer):
    """p-norm distance between row pairs (paddle.nn.PairwiseDistance)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        import paddle_tpu as P

        d = x - y
        return P.norm(d + self.epsilon, p=self.p, axis=-1,
                      keepdim=self.keepdim)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (paddle.nn.Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        import paddle_tpu as P

        return P.unflatten(x, self.axis, self.shape)
