"""paddle.nn.utils parity (`python/paddle/nn/utils/`): gradient clipping
helpers, parameter flattening, and weight/spectral-norm reparametrization
hooks.

TPU-first notes: clip helpers operate on eager `.grad` tensors (inside a
compiled train step, clipping belongs to the step's own global-norm code,
train_step.py); weight_norm/spectral_norm recompute the effective weight
in a forward-pre-hook, so they trace straight into jit programs.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = [
    "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
    "vector_to_parameters", "weight_norm", "remove_weight_norm",
    "spectral_norm",
]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Clip eager grads in place by global norm; returns the total norm
    (reference clip_grad_norm_.py)."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.asarray(0.0, jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.sum(jnp.stack([
            jnp.sum(jnp.abs(p.grad._value.astype(jnp.float32))
                    ** norm_type) for p in params])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"non-finite total norm {float(total)} in clip_grad_norm_")
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(total, 1e-12))
    for p in params:
        p.grad._value = (p.grad._value.astype(jnp.float32)
                         * scale).astype(p.grad._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """Clamp eager grads elementwise to [-clip_value, clip_value]."""
    for p in (parameters if isinstance(parameters, (list, tuple))
              else [parameters]):
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one 1-D tensor (transform_parameters.py)."""
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    """Scatter a flat vector back into the parameters (in-place rebind)."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._rebind(Tensor(v[off:off + n].reshape(tuple(p.shape))
                         .astype(p._value.dtype)))
        off += n
    if off != v.shape[0]:
        raise ValueError(f"vector has {v.shape[0]} elements; parameters "
                         f"need {off}")


def _norm_except_dim(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize `layer.name` as g * v/||v|| (weight_norm_hook.py).
    The effective weight is recomputed in a forward-pre-hook, so the
    reparametrization traces into compiled programs."""
    w = getattr(layer, name)
    if dim is None:
        dim = -1  # norm over everything
    wv = w._value
    if dim == -1:
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv.astype(jnp.float32))))
        g0 = g0.reshape((1,) * wv.ndim)
    else:
        g0 = _norm_except_dim(wv, dim)
    g = layer.create_parameter(list(g0.shape), dtype=str(wv.dtype))
    g._rebind(Tensor(g0.astype(wv.dtype)))
    v = layer.create_parameter(list(w.shape), dtype=str(wv.dtype))
    v._rebind(Tensor(wv))
    setattr(layer, name + "_g", g)
    setattr(layer, name + "_v", v)
    # the original becomes a derived (non-parameter) attribute
    if name in layer._parameters:
        del layer._parameters[name]

    def hook(lyr, inputs):
        vv = getattr(lyr, name + "_v")._value
        gg = getattr(lyr, name + "_g")._value.astype(jnp.float32)
        if dim == -1:
            nrm = jnp.sqrt(jnp.sum(jnp.square(vv.astype(jnp.float32))))
        else:
            nrm = _norm_except_dim(vv, dim)
        eff = (vv.astype(jnp.float32) / jnp.maximum(nrm, 1e-12) * gg)
        setattr(lyr, name, Tensor(eff.astype(vv.dtype)))
        return None

    hook(layer, None)  # materialize once immediately
    helper = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (helper, dim)
    return layer


def remove_weight_norm(layer, name="weight"):
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"no weight_norm hook on {name!r}")
    helper, dim = hooks.pop(name)
    helper.remove()
    eff = getattr(layer, name)  # last materialized effective weight
    w = layer.create_parameter(list(eff.shape), dtype=str(eff._value.dtype))
    w._rebind(Tensor(eff._value))
    setattr(layer, name, w)
    for suffix in ("_g", "_v"):
        pname = name + suffix
        if pname in layer._parameters:
            del layer._parameters[pname]
        if hasattr(layer, pname):
            delattr(layer, pname)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization hook (spectral_norm_hook.py): divides the
    weight by its largest singular value, estimated by power iteration
    on host-held u/v buffers updated each forward."""
    w = getattr(layer, name)
    wv = w._value
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    rng = np.random.RandomState(0)
    u = jnp.asarray(rng.randn(mat.shape[0]).astype(np.float32))
    u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    layer._sn_u = u

    v_param = layer.create_parameter(list(w.shape), dtype=str(wv.dtype))
    v_param._rebind(Tensor(wv))
    setattr(layer, name + "_orig", v_param)
    if name in layer._parameters:
        del layer._parameters[name]

    def hook(lyr, inputs):
        wv2 = getattr(lyr, name + "_orig")._value
        m = jnp.moveaxis(wv2, dim, 0).reshape(wv2.shape[dim], -1) \
            .astype(jnp.float32)
        u_ = lyr._sn_u
        for _ in range(n_power_iterations):
            v_ = m.T @ u_
            v_ = v_ / jnp.maximum(jnp.linalg.norm(v_), eps)
            u_ = m @ v_
            u_ = u_ / jnp.maximum(jnp.linalg.norm(u_), eps)
        from ...core import flags

        if not flags.in_trace():
            lyr._sn_u = u_  # persist the iterate only outside tracing
        sigma = u_ @ (m @ v_)
        eff = wv2.astype(jnp.float32) / jnp.maximum(sigma, eps)
        setattr(lyr, name, Tensor(eff.astype(wv2.dtype)))
        return None

    hook(layer, None)
    layer.register_forward_pre_hook(hook)
    return layer
