"""Common functionals: linear, embedding, dropout, padding, folding
(paddle.nn.functional.common parity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import rng as _rng
from ...core.dispatch import apply, op
from ...core.tensor import Tensor
from ...ops.manipulation import pad  # noqa: F401 (re-export)

__all__ = [
    "linear", "embedding", "bilinear", "dropout", "dropout2d", "dropout3d",
    "alpha_dropout", "pad", "unfold", "fold", "cosine_similarity",
    "label_smooth", "one_hot", "sequence_mask", "normalize",
]


@op("linear")
def linear(x, weight, bias=None, name=None):
    # weight layout [in, out] — matches the reference's nn.Linear storage
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@op("embedding")
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


@op("bilinear")
def bilinear(x1, x2, weight, bias=None, name=None):
    # weight: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if training or mode != "downscale_in_infer" or p == 0.0:
            return x if isinstance(x, Tensor) else Tensor(x)
        # downscale_in_infer: train uses the raw mask, infer scales by (1-p)
        return apply("dropout_infer", lambda v: v * (1.0 - p), x)
    key = _rng.split_for_op()

    def f(v, key):
        k = _rng.materialize(key)
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(k, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype)).astype(v.dtype)
        return jnp.where(keep, v, jnp.zeros((), v.dtype))

    return apply("dropout", f, x, key)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _rng.split_for_op()

    def f(v, key):
        k = _rng.materialize(key)
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        a = (1.0 / (scale * ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply("alpha_dropout", f, x, key)


@op("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    # x: [N, C, H, W] -> [N, C*kh*kw, L]
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    p = paddings
    if isinstance(p, int):
        pads = (p, p, p, p)
    elif len(p) == 2:
        pads = (p[0], p[0], p[1], p[1])
    else:
        pads = tuple(p)
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])))
    n, c, h, w = x.shape
    oh = (h - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w - (dw * (kw - 1) + 1)) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow)


@op("fold")
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    p = paddings
    if isinstance(p, int):
        pads = (p, p, p, p)
    elif len(p) == 2:
        pads = (p[0], p[0], p[1], p[1])
    else:
        pads = tuple(p)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    hh, ww = oh + pads[0] + pads[1], ow + pads[2] + pads[3]
    nh = (hh - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ww - (dw * (kw - 1) + 1)) // sw + 1
    out = jnp.zeros((n, c, hh, ww), x.dtype)
    xr = x.reshape(n, c, kh, kw, nh, nw)
    for i in range(kh):
        for j in range(kw):
            hs = i * dh
            ws = j * dw
            out = out.at[:, :, hs:hs + nh * sh:sh, ws:ws + nw * sw:sw].add(
                xr[:, :, i, j])
    return out[:, :, pads[0]:hh - pads[1], pads[2]:ww - pads[3]]


@op("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@op("one_hot")
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@op("sequence_mask")
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core import dtypes as _dt

    m = int(maxlen) if maxlen is not None else None
    if m is None:
        m = int(jnp.max(x))
    r = jnp.arange(m)
    mask = r[None, :] < x[..., None]
    return mask.astype(_dt.convert_dtype(dtype))


@op("normalize")
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers for partial-FC training (parity:
    `paddle.nn.functional.class_center_sample`). Positive classes are always
    kept; negatives fill up to num_samples. Host-side (data-dependent
    unique), like the reference's CPU path."""
    import numpy as np

    from ...core.tensor import Tensor

    lv = np.asarray(label._value if isinstance(label, Tensor)
                    else label).reshape(-1)
    pos = np.unique(lv)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        picked = np.random.RandomState(0).choice(
            neg_pool, size=num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, picked]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(remap[lv]), Tensor(sampled.astype(np.int64)))


__all__ += ["class_center_sample"]


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm distance along the last axis (reference distance.py)."""
    from ... import ops

    return ops.norm(x - y + epsilon, p=p, axis=-1, keepdim=keepdim)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W of a 4-D tensor; padding = [left, right, top, bottom]
    (reference zeropad2d)."""
    from ... import ops

    return ops.pad(x, padding, mode="constant", value=0.0,
                   data_format=data_format)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    from ... import ops

    return ops.temporal_shift(x, seg_num, shift_ratio, data_format)


def gather_tree(ids, parents):
    """Beam-search ancestor back-tracing (reference gather_tree):
    ids/parents [T, B, W] -> full sequences following parent pointers
    from the last step backward (lax.scan in reverse)."""
    def f(iv, pv):
        import jax

        t, b, w = iv.shape
        last_parent = jnp.broadcast_to(jnp.arange(w, dtype=pv.dtype),
                                       (b, w))

        def body(carry, xs):
            step_ids, step_parents = xs
            beam = carry  # [B, W] which beam to read at this step
            out = jnp.take_along_axis(step_ids, beam, axis=1)
            prev = jnp.take_along_axis(step_parents, beam, axis=1)
            return prev, out

        _, outs = jax.lax.scan(body, last_parent, (iv, pv), reverse=True)
        return outs

    return apply("gather_tree", f, ids, parents)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR sparsity pattern (reference
    sparse_attention, CUDA-only there). Each query row attends only to
    its CSR column set: columns are gathered per row, so compute is
    O(nnz·d) — static shapes (the CSR layout is fixed per call).

    query/key/value: [B, H, S, D]; offset: [B, H, S+1]; columns:
    [B, H, nnz]. Rows' column counts may vary; positions beyond a row's
    count are masked via the offset difference."""
    def f(q, k, v, off, cols):
        b, h, s, d = q.shape
        nnz = cols.shape[-1]
        counts = off[..., 1:] - off[..., :-1]           # [B, H, S]
        # per row r: its columns live at cols[off[r]:off[r+1]] — build a
        # [S, nnz] gather index with validity mask
        row_start = off[..., :-1]                        # [B, H, S]
        pos = jnp.arange(nnz)
        idx = row_start[..., None] + pos                 # [B, H, S, nnz]
        valid = pos < counts[..., None]
        idx = jnp.clip(idx, 0, nnz - 1)
        gathered_cols = jnp.take_along_axis(
            cols[..., None, :].repeat(s, axis=-2), idx, axis=-1)
        # gather k/v rows by advanced indexing per (b, h)
        bi = jnp.arange(b)[:, None, None, None]
        hi = jnp.arange(h)[None, :, None, None]
        kg = k[bi, hi, gathered_cols]                    # [B,H,S,nnz,D]
        vg = v[bi, hi, gathered_cols]
        scale = 1.0 / (d ** 0.5)
        logits = jnp.einsum("bhsd,bhsnd->bhsn",
                            q.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
        logits = jnp.where(valid, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhsn,bhsnd->bhsd", probs,
                         vg.astype(jnp.float32))
        return out.astype(q.dtype)

    return apply("sparse_attention", f, query, key, value,
                 sparse_csr_offset, sparse_csr_columns)


__all__ += ["pairwise_distance", "zeropad2d", "temporal_shift",
            "gather_tree", "sparse_attention"]
