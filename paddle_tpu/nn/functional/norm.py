"""Normalization functionals (paddle.nn.functional.norm parity). The fused
rms_norm/layer_norm fast paths swap in Pallas kernels on TPU (see
`paddle_tpu.ops.pallas`), mirroring `paddle/phi/kernels/fusion/gpu/
fused_layernorm_kernel.cu` / `incubate.nn.functional.fused_rms_norm`."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "layer_norm", "batch_norm", "instance_norm", "group_norm",
    "local_response_norm", "rms_norm",
]


@op("layer_norm")
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@op("rms_norm")
def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1,
             name=None):
    axis = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    axes = tuple(range(axis, x.ndim))
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    ms = jnp.mean(jnp.square(x32), axis=axes, keepdims=True)
    out = (x32 * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Stateful batch norm: updates running stats in-place during training
    (reference semantics: `paddle/phi/kernels/gpu/batch_norm_kernel.cu`)."""
    from ...core.dispatch import apply

    c_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    if x.ndim == 2:
        c_axis = 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    use_batch = training and not use_global_stats

    if use_batch:
        def f(v, w, b, rm, rv):
            v32 = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v
            mean = jnp.mean(v32, axis=axes)
            var = jnp.var(v32, axis=axes)
            shape = [1] * v.ndim
            shape[c_axis] = -1
            out = (v32 - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + epsilon)
            out = out.astype(v.dtype)
            if w is not None:
                out = out * w.reshape(shape)
            if b is not None:
                out = out + b.reshape(shape)
            return out, mean, var

        out, bmean, bvar = apply("batch_norm", f, x, weight, bias,
                                 running_mean, running_var)
        # update running stats (host-side state, like the reference's
        # mean_out/variance_out outputs written back to the same variable)
        m = momentum
        running_mean.set_value(
            m * running_mean._value + (1 - m) * bmean._value)
        n = 1
        for a in axes:
            n *= x.shape[a]
        unbiased = bvar._value * (n / max(1, n - 1))
        running_var.set_value(m * running_var._value + (1 - m) * unbiased)
        return out

    def g(v, w, b, rm, rv):
        shape = [1] * v.ndim
        shape[c_axis] = -1
        v32 = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v
        out = (v32 - rm.reshape(shape)) * jax.lax.rsqrt(
            rv.reshape(shape) + epsilon)
        out = out.astype(v.dtype)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out

    return apply("batch_norm_infer", g, x, weight, bias, running_mean,
                 running_var)


@op("instance_norm")
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1] * x.ndim
        shape[1] = -1
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1] * x.ndim
        shape[1] = -1
        out = out + bias.reshape(shape)
    return out


@op("group_norm")
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if data_format == "NHWC":
        x_t = jnp.moveaxis(x, -1, 1)
    else:
        x_t = x
    n, c = x_t.shape[0], x_t.shape[1]
    spatial = x_t.shape[2:]
    g = x_t.reshape((n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x_t.shape)
    shape = [1] * x_t.ndim
    shape[1] = -1
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


@op("local_response_norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[c_axis] = (half, size - half - 1)
    padded = jnp.pad(sq, pad_cfg)
    acc = jnp.zeros_like(x)
    for i in range(size):
        sl = [slice(None)] * x.ndim
        sl[c_axis] = slice(i, i + x.shape[c_axis])
        acc = acc + padded[tuple(sl)]
    div = jnp.power(k + alpha * acc, beta)
    return x / div
