"""Vision functionals (paddle.nn.functional.vision parity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = ["interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle",
           "channel_shuffle", "affine_grid", "grid_sample"]


@op("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if data_format in ("NHWC", "NWC", "NDHWC"):
        spatial = x.shape[1:-1]
        chan_last = True
    else:
        spatial = x.shape[2:]
        chan_last = False
    n_sp = len(spatial)
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * n_sp
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        size = [int(s) for s in (size if isinstance(size, (list, tuple))
                                 else [size])]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "bicubic": "cubic", "trilinear": "linear", "area": "linear"}[mode]
    if chan_last:
        new_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
        scale_axes = tuple(range(1, 1 + n_sp))
    else:
        new_shape = x.shape[:2] + tuple(size)
        scale_axes = tuple(range(2, 2 + n_sp))
    if mode == "nearest":
        # index-based nearest (matches reference's pixel mapping)
        out = x
        for i, ax in enumerate(scale_axes):
            in_sz = x.shape[ax]
            out_sz = size[i]
            idx = jnp.floor(jnp.arange(out_sz) * in_sz / out_sz).astype(jnp.int32)
            out = jnp.take(out, idx, axis=ax)
        return out
    return jax.image.resize(x, new_shape, method=jmode)


upsample = interpolate


@op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c // (r * r), r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, r, r, c // (r * r))
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(n, h * r, w * r, c // (r * r))


@op("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c, h // r, r, w // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    out = x.reshape(n, h // r, r, w // r, r, c)
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(n, h // r, w // r, c * r * r)


@op("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, groups, c // groups, h, w)
        out = out.transpose(0, 2, 1, 3, 4)
        return out.reshape(n, c, h, w)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, groups, c // groups)
    out = out.transpose(0, 1, 2, 4, 3)
    return out.reshape(n, h, w, c)


@op("affine_grid")
def affine_grid(theta, out_shape, align_corners=True, name=None):
    n, _, h, w = [int(s) for s in out_shape]
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2 / h - 1
        xs = (jnp.arange(w) + 0.5) * 2 / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # H,W,3
    grid = jnp.einsum("hwk,nck->nhwc", base, theta)
    return grid


@op("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1

    def gather(yy, xx):
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1)
        xc = jnp.clip(xx, 0, w - 1)
        # per-batch gather: vmap over n
        def one(img, yb, xb, vb):
            g = img[:, yb, xb]  # C, Hg, Wg
            return jnp.where(vb[None], g, 0.0)

        return jax.vmap(one)(x, yc, xc, valid)

    if mode == "nearest":
        xn = jnp.round(fx).astype(jnp.int32)
        yn = jnp.round(fy).astype(jnp.int32)
        return gather(yn, xn)

    wa = (x1 - fx) * (y1 - fy)
    wb = (x1 - fx) * (fy - y0)
    wc = (fx - x0) * (y1 - fy)
    wd = (fx - x0) * (fy - y0)
    va = gather(y0, x0)
    vb = gather(y1, x0)
    vc = gather(y0, x1)
    vd = gather(y1, x1)
    return (va * wa[:, None] + vb * wb[:, None] + vc * wc[:, None] +
            vd * wd[:, None]).astype(x.dtype)
