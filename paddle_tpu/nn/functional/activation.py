"""Activation functionals (paddle.nn.functional.activation parity:
`python/paddle/nn/functional/activation.py`). All map to VPU-friendly
elementwise XLA ops that fuse into adjacent matmuls."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core import rng as _rng

__all__ = [
    "relu", "relu_", "relu6", "leaky_relu", "prelu", "elu", "selu", "celu",
    "gelu", "silu", "swish", "mish", "hardswish", "hardsigmoid", "hardtanh",
    "hardshrink", "softshrink", "tanhshrink", "softsign", "softplus",
    "softmax", "log_softmax", "log_sigmoid", "sigmoid", "tanh", "glu",
    "gumbel_softmax", "maxout", "rrelu", "thresholded_relu", "swiglu",
]


@op("relu")
def relu(x, name=None):
    return jnp.maximum(x, 0)


def relu_(x, name=None):
    return x._rebind(relu(x))


@op("relu6")
def relu6(x, name=None):
    return jnp.clip(x, 0, 6)


@op("leaky_relu")
def leaky_relu(x, negative_slope=0.01, name=None):
    return jnp.where(x >= 0, x, negative_slope * x)


@op("prelu")
def prelu(x, weight, data_format="NCHW", name=None):
    if weight.size > 1:
        shape = [1] * x.ndim
        axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape[axis] = weight.shape[0]
        weight = weight.reshape(shape)
    return jnp.where(x >= 0, x, weight * x)


@op("elu")
def elu(x, alpha=1.0, name=None):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op("celu")
def celu(x, alpha=1.0, name=None):
    return jnp.maximum(x, 0) + jnp.minimum(0, alpha * jnp.expm1(x / alpha))


@op("gelu")
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


@op("silu")
def silu(x, name=None):
    return x * jax.nn.sigmoid(x)


@op("swish")
def swish(x, name=None):
    return x * jax.nn.sigmoid(x)


@op("mish")
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@op("hardswish")
def hardswish(x, name=None):
    return x * jnp.clip(x + 3, 0, 6) / 6


@op("hardsigmoid")
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0, 1)


@op("hardtanh")
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


@op("hardshrink")
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


@op("softshrink")
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0))


@op("tanhshrink")
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@op("softsign")
def softsign(x, name=None):
    return x / (1 + jnp.abs(x))


@op("softplus")
def softplus(x, beta=1, threshold=20, name=None):
    # double-where: keep the untaken exp branch finite (where-grad trap)
    big = x * beta > threshold
    safe = jnp.where(big, jnp.zeros((), x.dtype), x)
    return jnp.where(big, x, jnp.log1p(jnp.exp(beta * safe)) / beta)


@op("softmax")
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core import dtypes as _dt

        x = x.astype(_dt.convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax")
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core import dtypes as _dt

        x = x.astype(_dt.convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


@op("log_sigmoid")
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@op("sigmoid")
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@op("tanh")
def tanh(x, name=None):
    return jnp.tanh(x)


@op("glu")
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@op("swiglu")
def swiglu(x, y=None, name=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return x * jax.nn.sigmoid(x) * y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.dispatch import apply

    key = _rng.split_for_op()

    def f(v, key):
        k = _rng.materialize(key)
        g = jax.random.gumbel(k, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(
                y_hard, idx, jnp.asarray(1.0, y.dtype), axis=axis,
                inplace=False)
            # straight-through estimator
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply("gumbel_softmax", f, x, key)


@op("maxout")
def maxout(x, groups, axis=1, name=None):
    axis = axis % x.ndim
    c = x.shape[axis]
    # reference layout (nn/functional/activation.py maxout docstring):
    # out[..., j, ...] = max_k x[..., j + (c//groups)*k, ...] — the groups
    # dim is the OUTER factor of the channel axis
    new_shape = x.shape[:axis] + (groups, c // groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...core.dispatch import apply

    if not training:
        neg = (lower + upper) / 2.0
        return leaky_relu(x, neg)
    key = _rng.split_for_op()

    def f(v, key):
        k = _rng.materialize(key)
        a = jax.random.uniform(k, v.shape, v.dtype, lower, upper)
        return jnp.where(v >= 0, v, a * v)

    return apply("rrelu", f, x, key)


@op("thresholded_relu")
def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return jnp.where(x > threshold, x, value)


def elu_(x, alpha=1.0, name=None):
    return x._rebind(elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    return x._rebind(hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return x._rebind(leaky_relu(x, negative_slope))


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._rebind(softmax(x, axis, dtype))


def tanh_(x, name=None):
    from ... import ops

    return x._rebind(ops.tanh(x))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    return x._rebind(thresholded_relu(x, threshold, value))


__all__ += ["elu_", "hardtanh_", "leaky_relu_", "softmax_", "tanh_",
            "thresholded_relu_"]
