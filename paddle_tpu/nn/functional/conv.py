"""Convolutions (paddle.nn.functional.conv parity). All lower to
`lax.conv_general_dilated`, which XLA tiles onto the MXU — the TPU analog of
the reference's cuDNN dispatch (`paddle/phi/kernels/gpudnn/conv_kernel.cu`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v if len(v) == n else tuple(v[i % len(v)] for i in range(n))


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dimnums(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last):
    dn = _dimnums(n, channel_last)
    if channel_last:
        # weights are stored OI... (paddle layout); transpose for channel-last
        perm = tuple(range(2, 2 + n)) + (1, 0)
        weight = jnp.transpose(weight, perm)
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=_tup(stride, n),
        padding=_pad_cfg(padding, n),
        rhs_dilation=_tup(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )
    if bias is not None:
        shape = [1] * out.ndim
        shape[-1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@op("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format in ("NLC",))


@op("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format == "NHWC")


@op("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last, output_size=None):
    dn = _dimnums(n, channel_last)
    strides = _tup(stride, n)
    dil = _tup(dilation, n)
    opad = _tup(output_padding, n)
    # paddle weight layout for transpose conv: [in, out/groups, *k]
    k = weight.shape[2:]
    if isinstance(padding, str):
        pad_cfg = padding.upper()
        lo_hi = None
    else:
        lo_hi = _pad_cfg(padding, n)

    if lo_hi is not None:
        # transpose-conv padding math: pad = dilation*(k-1) - pad
        pad_cfg = [
            (dil[i] * (k[i] - 1) - lo_hi[i][0],
             dil[i] * (k[i] - 1) - lo_hi[i][1] + opad[i])
            for i in range(n)
        ]
    # flip spatial dims & swap io: OIHW expected with O=out
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if groups > 1:
        ci = w.shape[0]
        co_g = w.shape[1]
        w = w.reshape((groups, ci // groups) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)  # g, co_g, ci_g, *k
        w = w.reshape((groups * co_g, ci // groups) + tuple(k))
    else:
        w = jnp.swapaxes(w, 0, 1)
    if channel_last:
        perm = tuple(range(2, 2 + n)) + (1, 0)
        w = jnp.transpose(w, perm)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=(1,) * n,
        padding=pad_cfg,
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        shape = [1] * out.ndim
        shape[-1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@op("conv1d_transpose")
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC",
                           output_size)


@op("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC",
                           output_size)


@op("conv3d_transpose")
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC",
                           output_size)
