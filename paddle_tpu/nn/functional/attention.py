"""Attention functionals.

Parity targets: `paddle.nn.functional.scaled_dot_product_attention` /
`flash_attention` (python/paddle/nn/functional/flash_attention.py:146, backed
by third_party/flashattn CUDA kernels) and the fused rope op
(`paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu`).

TPU-first: on TPU the flash path dispatches a Pallas blockwise-softmax kernel
(`paddle_tpu.ops.pallas.flash_attention`); elsewhere a jnp reference
implementation with identical semantics.
"""
from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply, op

__all__ = [
    "scaled_dot_product_attention", "flash_attention",
    "flash_attn_unpadded", "sdp_kernel",
    "fused_rotary_position_embedding", "apply_rotary_pos_emb",
]

# sdp_kernel() dispatch policy (reference flash_attention.py:27): which
# backends scaled_dot_product_attention may pick. On TPU there are two
# real tiers: the Pallas flash kernel and the jnp math path (the
# mem_efficient flag maps onto flash — one fused tier owns both roles).
_sdp_policy = {"math": True, "flash": True}


@contextlib.contextmanager
def sdp_kernel(enable_math=False, enable_flash=True,
               enable_mem_efficient=True):
    """Constrain scaled_dot_product_attention's kernel choice inside the
    context (reference sdp_kernel). enable_flash/enable_mem_efficient
    both gate the fused Pallas tier; enable_math the jnp reference."""
    global _sdp_policy
    old = _sdp_policy
    _sdp_policy = {"math": bool(enable_math),
                   "flash": bool(enable_flash or enable_mem_efficient)}
    try:
        yield
    finally:
        _sdp_policy = old


def _sdpa_ref(q, k, v, attn_mask, dropout_p, is_causal, scale):
    # q,k,v: [B, S, H, D] (paddle flash-attention layout); GQA inputs
    # (fewer KV heads) expand here — the Pallas path reads them grouped.
    # Flat-layout spelling: the einsums contract on the native [B,S,H,D]
    # operands directly (dot_general batches over non-leading (b, h)),
    # so only the [B,H,Sq,D] -> [B,Sq,H,D] output reorder remains as an
    # explicit transpose. Same contraction order as the old swapaxes
    # form — bit-identical values; this is what the PT401 budget for
    # the CPU-audited train step measures (tools/perf_budget.json).
    from ...ops.pallas.flash_attention import _expand_gqa_kv

    q, k, v = _expand_gqa_kv(q, k, v)
    d = q.shape[-1]
    scale = scale or (1.0 / math.sqrt(d))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs [batch, seq, heads, head_dim] (reference layout)."""
    from ...ops import pallas as _pl

    # masks that need no gradient may stream through the biased fused
    # kernels; a trainable mask (stop_gradient=False) keeps the
    # reference path, which differentiates through the bias
    # default FALSE for attribute-less masks (raw arrays/tracers):
    # routing an unknown mask to the zero-cotangent biased kernel would
    # silently kill a trainable bias's gradient
    mask_sg = attn_mask is None or bool(
        getattr(attn_mask, "stop_gradient", False))

    def f(q, k, v, m):
        if _sdp_policy["flash"] and _pl.flash_attention_available(q):
            return _pl.flash_attention_fwd(q, k, v, m, is_causal,
                                           bias_grad_safe=mask_sg)
        if _sdp_policy["flash"]:
            # flash requested but unavailable for this input/backend —
            # the dispatch-tier fallback that used to be silent
            from ...observability import metrics as _obs_metrics

            _obs_metrics.inc("flash.dispatch", tier="fallback")
            _obs_metrics.inc("flash.fallback_reason",
                             reason="unavailable")
        if not _sdp_policy["math"]:
            # math disabled and flash unavailable (or also disabled):
            # falling through to the reference path would silently
            # violate the sdp_kernel policy
            raise RuntimeError(
                "sdp_kernel: math backend disabled and the flash "
                "(Pallas) kernel is "
                + ("unavailable for this input (CPU/interpret mode or "
                   "unsupported shape/dtype)"
                   if _sdp_policy["flash"] else "also disabled"))
        return _sdpa_ref(q, k, v, m, dropout_p, is_causal, None)

    return apply("scaled_dot_product_attention", f, query, key, value,
                 attn_mask)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="",
                        training=True, name=None):
    """Packed ragged-batch attention (reference
    `flash_attention.py:302`): query/key/value [total_seq_len, H, D],
    cu_seqlens_* [n+1] cumulative lengths. Returns (out, softmax) with
    softmax None unless return_softmax (never materialized here).

    TPU-first: segment-masked Pallas kernels
    (`ops.pallas.varlen_attention`) — the ragged batch runs
    block-diagonal with static shapes; no per-sequence loop, no T x T
    mask. Attention-probability dropout is not applied on this path
    (the fused kernel never materializes probabilities); `dropout` is
    accepted for signature parity.
    """
    if return_softmax:
        raise NotImplementedError(
            "flash_attn_unpadded: return_softmax=True would materialize "
            "the T x T probabilities the fused kernel exists to avoid")
    if dropout and training:
        raise NotImplementedError(
            "flash_attn_unpadded: attention-probability dropout is not "
            "applied on the fused path (probabilities never materialize); "
            "pass dropout=0 and regularize elsewhere, or use "
            "scaled_dot_product_attention's reference path")
    if causal:
        # per-sequence causal alignment needs IDENTICAL packings: the
        # kernel's one global diagonal offset cannot express the
        # reference's bottom-right alignment across differently-packed
        # q/k (e.g. chunked prefill) — fail loudly, never silently
        import numpy as _np

        try:
            cq = _np.asarray(cu_seqlens_q.numpy()
                             if hasattr(cu_seqlens_q, "numpy")
                             else cu_seqlens_q)
            ck = _np.asarray(cu_seqlens_k.numpy()
                             if hasattr(cu_seqlens_k, "numpy")
                             else cu_seqlens_k)
            same = cq.shape == ck.shape and bool((cq == ck).all())
        except Exception:
            same = None  # traced values: cannot validate here — a
            # jitted call with mismatched packings computes the wrong
            # causal alignment undetected (documented hole; validate
            # packings before jit, or pass concrete cu_seqlens)
        if same is False:
            raise NotImplementedError(
                "flash_attn_unpadded: causal=True requires identical "
                "cu_seqlens_q and cu_seqlens_k (per-sequence causal "
                "alignment across different packings is not supported). "
                "NOTE: this check only runs on concrete cu_seqlens — "
                "under jit the values are traced and a mismatch cannot "
                "be detected; validate before tracing.")
    from ...ops.pallas.varlen_attention import varlen_attention

    def f(q, k, v, cu_q, cu_k):
        return varlen_attention(q, k, v, cu_q, cu_k, scale=scale,
                                causal=causal)

    out = apply("flash_attn_unpadded", f, query, key, value,
                cu_seqlens_q, cu_seqlens_k)
    return out, None


def _rope_rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _rope_rotate_interleaved(x):
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    out = jnp.stack([-x2, x1], axis=-1)
    return out.reshape(x.shape)


@op("fused_rotary_position_embedding")
def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """q/k/v: [B, S, H, D]. Matches incubate.nn.functional.
    fused_rotary_position_embedding semantics (fused_rope_kernel.cu)."""
    if time_major:
        raise NotImplementedError(
            "fused_rotary_position_embedding: time_major=True ([S, B, ...]"
            " layout) is not supported — pass batch-major tensors")
    b, s, h, d = q.shape
    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        if position_ids is not None:
            # explicit positions (decode offsets): build phases per position
            pos = jnp.asarray(position_ids).astype(jnp.float32)  # [B, S]
            freqs = pos[:, :, None] * inv[None, None, :]         # [B,S,D/2]
            emb = jnp.concatenate([freqs, freqs], axis=-1)
            cos = jnp.cos(emb)[:, :, None, :]
            sin = jnp.sin(emb)[:, :, None, :]
            position_ids = None  # consumed
        else:
            t = jnp.arange(s, dtype=jnp.float32)
            freqs = jnp.outer(t, inv)  # [S, D/2]
            emb = jnp.concatenate([freqs, freqs], axis=-1)
            cos = jnp.cos(emb)[None, :, None, :]
            sin = jnp.sin(emb)[None, :, None, :]
    else:
        cos = jnp.reshape(cos, (1, -1, 1, d))
        sin = jnp.reshape(sin, (1, -1, 1, d))
    if position_ids is not None:
        cos = jnp.squeeze(cos, axis=(0, 2))[position_ids][:, :, None, :]
        sin = jnp.squeeze(sin, axis=(0, 2))[position_ids][:, :, None, :]
    cos = cos.astype(q.dtype)
    sin = sin.astype(q.dtype)

    rot = _rope_rotate_half if use_neox_rotary_style else \
        _rope_rotate_interleaved

    def emb_one(x):
        if x is None:
            return None
        if use_neox_rotary_style:
            from ...ops.pallas.rope import rope_available, rope_pallas

            if rope_available(x):
                return rope_pallas(x, cos, sin)
        return x * cos + rot(x) * sin

    return tuple(emb_one(x) for x in (q, k, v))


def apply_rotary_pos_emb(q, k, cos, sin, position_ids=None):
    out = fused_rotary_position_embedding(q, k, None, sin=sin, cos=cos,
                                          position_ids=position_ids)
    return out[0], out[1]
