"""Loss functionals (paddle.nn.functional.loss parity:
`python/paddle/nn/functional/loss.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply, op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "square_error_cost",
    "sigmoid_focal_loss", "hinge_embedding_loss", "cosine_embedding_loss",
    "triplet_margin_loss", "log_loss", "npair_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


@op("cross_entropy")
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    logits = input
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    n_classes = logits.shape[axis]
    if soft_label or (label.ndim == logits.ndim and
                      label.shape[axis] == n_classes and
                      jnp.issubdtype(label.dtype, jnp.floating)):
        soft = label
        if label_smoothing:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(soft * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(soft * weight, axis=axis)
            loss = loss * w
        return _reduce(loss, reduction)
    lab = label
    if lab.ndim == logits.ndim and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis)
    lab = lab.astype(jnp.int32)
    valid = lab != ignore_index
    safe_lab = jnp.where(valid, lab, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_lab, axis), axis=axis)
    picked = jnp.squeeze(picked, axis)
    if label_smoothing:
        smooth_loss = -jnp.mean(logp, axis=axis)
        loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
    else:
        loss = -picked
    if weight is not None:
        w = weight[safe_lab]
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if weight is not None:
            denom = jnp.sum(jnp.where(valid, weight[safe_lab], 0.0))
        else:
            denom = jnp.sum(valid.astype(loss.dtype))
        return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if loss.ndim < logits.ndim else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


@op("mse_loss")
def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.square(input - label), reduction)


@op("l1_loss")
def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.abs(input - label), reduction)


@op("nll_loss")
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lab = label.astype(jnp.int32)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    picked = jnp.take_along_axis(input, jnp.expand_dims(safe, 1), axis=1)
    loss = -jnp.squeeze(picked, 1)
    if weight is not None:
        loss = loss * weight[safe]
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.sum(weight[safe] * valid) if weight is not None else \
            jnp.sum(valid)
        return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


@op("binary_cross_entropy")
def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    max_val = jnp.maximum(-logit, 0)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + max_val + \
            jnp.log(jnp.exp(-max_val) + jnp.exp(-logit - max_val))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op("kl_div")
def kl_div(input, label, reduction="mean", log_target=False, name=None):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        out = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
        loss = jnp.where(label > 0, out, 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@op("smooth_l1_loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce(loss, reduction)


@op("margin_ranking_loss")
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    loss = jnp.maximum(-label * (input - other) + margin, 0)
    return _reduce(loss, reduction)


@op("square_error_cost")
def square_error_cost(input, label):
    return jnp.square(input - label)


@op("sigmoid_focal_loss")
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    p = jax.nn.sigmoid(logit)
    ce = (1 - label) * logit + jnp.maximum(-logit, 0) + \
        jnp.log(jnp.exp(-jnp.abs(logit)) + 1)
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        alpha_t = alpha * label + (1 - alpha) * (1 - label)
        loss = alpha_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@op("hinge_embedding_loss")
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    loss = jnp.where(label == 1.0, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


@op("cosine_embedding_loss")
def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


@op("triplet_margin_loss")
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.abs(a - b) ** p, axis=-1) + epsilon,
                         1.0 / p)

    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(d_pos - d_neg + margin, 0)
    return _reduce(loss, reduction)


@op("log_loss")
def log_loss(input, label, epsilon=1e-4, name=None):
    return -label * jnp.log(input + epsilon) - \
        (1 - label) * jnp.log(1 - input + epsilon)


@op("npair_loss")
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), 1))) / 4
    sim = anchor @ positive.T
    lab = labels.reshape(-1, 1) == labels.reshape(1, -1)
    lab = lab.astype(sim.dtype)
    lab = lab / jnp.sum(lab, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(lab * logp, axis=1))
    return ce + reg


@op("poisson_nll_loss")
def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label + epsilon) - label + \
            0.5 * jnp.log(2 * jnp.pi * (label + epsilon))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@op("multi_label_soft_margin_loss")
def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    loss = -(label * jax.nn.log_sigmoid(input) +
             (1 - label) * jax.nn.log_sigmoid(-input))
    loss = jnp.mean(loss, axis=-1)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op("soft_margin_loss")
def soft_margin_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.log1p(jnp.exp(-label * input)), reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (parity: `paddle.nn.functional.ctc_loss`, reference kernel
    third_party warpctc via `warpctc` op).

    TPU-first: the forward algorithm runs as a `lax.scan` over time with the
    [B, 2L+1] extended-label lattice vectorized per batch — log-space
    recursion, no host loop; grads come from jax autodiff through the scan
    (the reference ships a hand-written backward).

    log_probs: [T, B, C] log-softmax scores; labels: [B, L] padded.
    """
    def f(lp, lab, in_len, lab_len):
        t_max, b, c = lp.shape
        l_max = lab.shape[1]
        s_max = 2 * l_max + 1
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        lp = lp.astype(jnp.float32)

        # extended label sequence: blank interleaved
        ext = jnp.full((b, s_max), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
        allow_skip = (ext != blank) & (ext != prev2)

        in_len = in_len.astype(jnp.int32).reshape(b)
        lab_len = lab_len.astype(jnp.int32).reshape(b)

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where(
            jnp.arange(s_max)[None, :] < 2, emit0, neg_inf)
        # s=1 only valid if label_len > 0
        alpha0 = jnp.where(
            (jnp.arange(s_max)[None, :] == 1) & (lab_len[:, None] == 0),
            neg_inf, alpha0)

        def step(alpha, inp):
            lp_t, t = inp
            a1 = alpha
            a2 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)),
                         constant_values=-1e30)
            a3 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)),
                         constant_values=-1e30)
            a3 = jnp.where(allow_skip, a3, neg_inf)
            m = jnp.maximum(jnp.maximum(a1, a2), a3)
            summed = m + jnp.log(
                jnp.exp(a1 - m) + jnp.exp(a2 - m) + jnp.exp(a3 - m))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new = summed + emit
            new = jnp.where(t < in_len[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(
            step, alpha0, (lp[1:], jnp.arange(1, t_max)))

        last = 2 * lab_len  # index of final blank
        a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
        a_prev = jnp.where(lab_len > 0, a_prev, neg_inf)
        m = jnp.maximum(a_last, a_prev)
        ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
        loss = -ll
        if norm_by_times:  # warpctc semantics: per-sample / input length
            loss = loss / jnp.maximum(in_len, 1).astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1).astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("ctc_loss", f, log_probs, labels, input_lengths,
                 label_lengths)


__all__.append("ctc_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax CE (parity: `paddle.nn.functional.
    margin_cross_entropy`, phi `margin_cross_entropy` kernel).

    logits are cosine similarities; the target class gets
    cos(m1·θ + m2) − m3 before scaling. Model-parallel class sharding is
    expressed with sharded logits under jit (mesh 'mp' axis) instead of the
    reference's per-rank comm kernel."""
    if group is not None:
        raise NotImplementedError(
            "margin_cross_entropy: explicit process groups are not used "
            "on TPU — shard the class dim over the 'mp' mesh axis under "
            "jit and XLA inserts the cross-shard softmax collectives")
    def f(lg, lb):
        lb = lb.reshape(-1).astype(jnp.int32)
        n, c = lg.shape
        onehot = jax.nn.one_hot(lb, c, dtype=lg.dtype)
        theta = jnp.arccos(jnp.clip(lg, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        mod = jnp.where(onehot > 0, target, lg) * scale
        logp = jax.nn.log_softmax(mod, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
        sm = jnp.exp(logp)
        if reduction == "mean":
            out = jnp.mean(loss)
        elif reduction == "sum":
            out = jnp.sum(loss)
        else:
            out = loss
        return (out, sm) if return_softmax else out

    return apply("margin_cross_entropy", f, logits, label)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (parity: `paddle.nn.functional.rnnt_loss`,
    reference kernel third_party warprnnt via `warprnnt` op).

    TPU-first: the (T, U) lattice forward recursion runs as an outer
    `lax.scan` over time with an inner scan over the label axis (the u
    recurrence is sequential); log-space throughout, grads via autodiff.

    input: [B, T, U+1, V] joint-network logits; label: [B, U] padded.
    fastemit_lambda: FastEmit regularization weight — not implemented;
    only 0 (or the paddle default 0.001 explicitly zeroed by the caller)
    is honored loudly.
    """
    if fastemit_lambda:
        import warnings

        warnings.warn(
            "rnnt_loss: fastemit_lambda regularization is not applied in "
            "this build (plain RNNT objective); pass fastemit_lambda=0 "
            "to silence", stacklevel=2)
    def f(logits, lab, in_len, lab_len):
        b, t_max, u1, v = logits.shape
        u_max = u1 - 1
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        blank_lp = logp[..., blank]                       # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            logp[:, :, :u_max, :],
            lab.astype(jnp.int32)[:, None, :, None], axis=-1)[..., 0]
        # mask emits beyond each row's label length
        upos = jnp.arange(u_max)[None, None, :]
        emit_lp = jnp.where(upos < lab_len.reshape(b, 1, 1), emit_lp,
                            neg_inf)

        in_len = in_len.astype(jnp.int32).reshape(b)
        lab_len = lab_len.astype(jnp.int32).reshape(b)

        # row at t=0: pure emission prefix sums
        row0 = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.float32),
             jnp.cumsum(emit_lp[:, 0, :], axis=-1)], axis=-1)

        def time_step(row_prev, inp):
            blank_prev, emit_t, t = inp
            top = row_prev + blank_prev        # [B, U+1]

            def u_step(c, xu):
                top_u, emit_u = xu
                m = jnp.maximum(top_u, c + emit_u)
                c_new = m + jnp.log(jnp.exp(top_u - m)
                                    + jnp.exp(c + emit_u - m))
                return c_new, c_new

            c0 = top[:, 0]
            _, rest = jax.lax.scan(
                u_step, c0,
                (jnp.swapaxes(top[:, 1:], 0, 1),
                 jnp.swapaxes(emit_t, 0, 1)))
            row = jnp.concatenate([c0[:, None],
                                   jnp.swapaxes(rest, 0, 1)], axis=-1)
            row = jnp.where(t < in_len[:, None], row, row_prev)
            return row, None

        row, _ = jax.lax.scan(
            time_step, row0,
            (jnp.swapaxes(blank_lp[:, :-1], 0, 1)[: t_max - 1]
             if t_max > 1 else jnp.zeros((0, b, u1)),
             jnp.swapaxes(emit_lp[:, 1:], 0, 1) if t_max > 1
             else jnp.zeros((0, b, u_max)),
             jnp.arange(1, t_max)))

        final_alpha = jnp.take_along_axis(row, lab_len[:, None],
                                          axis=1)[:, 0]
        tb = jnp.clip(in_len - 1, 0)
        final_blank = blank_lp[jnp.arange(b), tb, lab_len]
        loss = -(final_alpha + final_blank)
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("rnnt_loss", f, input, label, input_lengths, label_lengths)


__all__ += ["margin_cross_entropy", "rnnt_loss"]


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss (reference nn/functional/loss.py dice_loss): input
    [N, ..., C] probabilities, label [N, ..., 1] class ids."""
    def f(x, y):
        import jax

        c = x.shape[-1]
        y1 = jax.nn.one_hot(y.reshape(y.shape[:-1]), c, dtype=x.dtype)
        flat_x = x.reshape(x.shape[0], -1)
        flat_y = y1.reshape(y1.shape[0], -1)
        inter = jnp.sum(flat_x * flat_y, axis=1)
        union = jnp.sum(flat_x, axis=1) + jnp.sum(flat_y, axis=1)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", f, input, label)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over the default complete binary tree
    (reference hsigmoid_loss): num_classes leaves, num_classes-1 internal
    nodes; each class's root-to-leaf path comes from its binary coding."""
    import numpy as _np

    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom-tree hsigmoid (path_table/path_code) is not wired; "
            "the default complete-binary-tree path is supported")
    depth = max(1, int(_np.ceil(_np.log2(max(num_classes, 2)))))
    # per class: sequence of (node_index, code) top-down, padded
    tables, codes, masks = [], [], []
    for cls in range(num_classes):
        node = cls + num_classes  # leaf id in heap order
        path = []
        while node > 1:
            path.append((node // 2 - 1, node % 2))  # internal idx, code
            node //= 2
        path = path[::-1]
        pad = depth - len(path)
        tables.append([p[0] for p in path] + [0] * pad)
        codes.append([p[1] for p in path] + [0] * pad)
        masks.append([1.0] * len(path) + [0.0] * pad)
    t = jnp.asarray(_np.asarray(tables, _np.int32))
    c = jnp.asarray(_np.asarray(codes, _np.float32))
    m = jnp.asarray(_np.asarray(masks, _np.float32))

    def f(x, y, w, b):
        yy = y.reshape(-1).astype(jnp.int32)
        nodes = t[yy]                      # [N, depth]
        code = c[yy]
        mask = m[yy]
        wn = w[nodes]                      # [N, depth, D]
        logit = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                           wn.astype(jnp.float32))
        if b is not None:
            logit = logit + b.reshape(-1)[nodes]
        # BCE with target = code
        per = jnp.maximum(logit, 0) - logit * code + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
        return jnp.mean(jnp.sum(per * mask, axis=1))

    if bias is None:
        return apply("hsigmoid_loss", lambda x, y, w: f(x, y, w, None),
                     input, label, weight)
    return apply("hsigmoid_loss", f, input, label, weight, bias)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Reference triplet_margin_with_distance_loss: arbitrary distance fn
    (default p2 pairwise distance)."""
    if distance_function is None:
        def distance_function(a, b):
            from ... import ops

            return ops.norm(a - b, p=2, axis=-1)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        from ... import ops as _ops

        d_neg = _ops.minimum(d_neg, d_pn)
    loss = (d_pos - d_neg + margin).clip(min=0.0)
    from ...core.dispatch import apply as _apply

    return _apply("triplet_margin_with_distance_loss",
                  lambda lv: _reduce(lv, reduction), loss)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Reference multi_margin_loss: hinge loss vs the true-class score."""
    def f(x, y, w):
        n, c = x.shape
        yy = y.reshape(-1).astype(jnp.int32)
        true = jnp.take_along_axis(x, yy[:, None], axis=1)
        m = jnp.maximum(0.0, margin - true + x) ** p
        if w is not None:
            m = m * w.reshape(-1)[yy][:, None]
        m = m * (1 - jax_nn_one_hot(yy, c, x.dtype))
        return jnp.sum(m, axis=1) / c

    def jax_nn_one_hot(i, c, dt):
        import jax

        return jax.nn.one_hot(i, c, dtype=dt)

    if weight is None:
        return apply("multi_margin_loss",
                     lambda x, y: _reduce(f(x, y, None), reduction),
                     input, label)
    return apply("multi_margin_loss",
                 lambda x, y, w: _reduce(f(x, y, w), reduction),
                 input, label, weight)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Reference gaussian_nll_loss: negative log likelihood of label
    under N(input, variance)."""
    def f(mu, y, var):
        import math as _math

        v = jnp.maximum(var.astype(jnp.float32), epsilon)
        out = 0.5 * (jnp.log(v) +
                     (y.astype(jnp.float32) - mu.astype(jnp.float32)) ** 2
                     / v)
        if full:
            out = out + 0.5 * _math.log(2 * _math.pi)
        return out

    return apply("gaussian_nll_loss",
                 lambda mu, y, var: _reduce(f(mu, y, var), reduction),
                 input, label, variance)


__all__ += ["dice_loss", "hsigmoid_loss",
            "triplet_margin_with_distance_loss", "multi_margin_loss",
            "gaussian_nll_loss"]
