"""Pooling functionals (paddle.nn.functional.pooling parity) — lowered to
`lax.reduce_window`, XLA's native pooling primitive."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
]


def _tup(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pool(x, ksize, stride, padding, n, mode, ceil_mode=False,
          exclusive=True, channel_last=False):
    k = _tup(ksize, n)
    s = _tup(stride, n) or k
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tup(padding, n) if not isinstance(padding, int) else (padding,) * n
        pad = [(pp, pp) for pp in p]
    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = pad if isinstance(pad, str) else [(0, 0)] + list(pad) + [(0, 0)]
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)
    if mode == "max":
        init = -np.inf if jnp.issubdtype(x.dtype, np.floating) else \
            np.iinfo(np.dtype(x.dtype)).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                     pads)
    # avg
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                                   window, strides, pads)
    if exclusive and isinstance(pads, list) and any(p != (0, 0) for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides, pads)
        return summed / counts
    return summed / float(np.prod(k))


@op("avg_pool1d")
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", ceil_mode,
                 exclusive)


@op("avg_pool2d")
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", ceil_mode,
                 exclusive, data_format == "NHWC")


@op("avg_pool3d")
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", ceil_mode,
                 exclusive, data_format == "NDHWC")


@op("max_pool1d")
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "max", ceil_mode)


@op("max_pool2d")
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                 channel_last=data_format == "NHWC")


@op("max_pool3d")
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                 channel_last=data_format == "NDHWC")


def _adaptive(x, output_size, n, mode):
    out_sz = _tup(output_size, n)
    spatial = x.shape[2:]
    out = x
    # decompose into per-axis windows when evenly divisible; general case uses
    # mean/max over index buckets
    if all(s % o == 0 for s, o in zip(spatial, out_sz)):
        k = tuple(s // o for s, o in zip(spatial, out_sz))
        window = (1, 1) + k
        if mode == "max":
            return jax.lax.reduce_window(x, -np.inf, jax.lax.max, window,
                                         window, "VALID")
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, window,
                                       "VALID")
        return summed / float(np.prod(k))
    # uneven: gather per output cell (small output sizes typical)
    for ax, o in enumerate(out_sz):
        dim = out.shape[2 + ax]
        starts = [int(np.floor(i * dim / o)) for i in range(o)]
        ends = [int(np.ceil((i + 1) * dim / o)) for i in range(o)]
        pieces = []
        for s_, e_ in zip(starts, ends):
            sl = [slice(None)] * out.ndim
            sl[2 + ax] = slice(s_, e_)
            seg = out[tuple(sl)]
            red = jnp.max(seg, axis=2 + ax, keepdims=True) if mode == "max" \
                else jnp.mean(seg, axis=2 + ax, keepdims=True)
            pieces.append(red)
        out = jnp.concatenate(pieces, axis=2 + ax)
    return out


@op("adaptive_avg_pool1d")
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


@op("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg")


@op("adaptive_avg_pool3d")
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg")


@op("adaptive_max_pool1d")
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


@op("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


@op("adaptive_max_pool3d")
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")
