"""Pooling functionals (paddle.nn.functional.pooling parity) — lowered to
`lax.reduce_window`, XLA's native pooling primitive."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
]


def _tup(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pool(x, ksize, stride, padding, n, mode, ceil_mode=False,
          exclusive=True, channel_last=False):
    k = _tup(ksize, n)
    s = _tup(stride, n) or k
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tup(padding, n) if not isinstance(padding, int) else (padding,) * n
        pad = [(pp, pp) for pp in p]
    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = pad if isinstance(pad, str) else [(0, 0)] + list(pad) + [(0, 0)]
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)
    if mode == "max":
        init = -np.inf if jnp.issubdtype(x.dtype, np.floating) else \
            np.iinfo(np.dtype(x.dtype)).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                     pads)
    # avg
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                                   window, strides, pads)
    if exclusive and isinstance(pads, list) and any(p != (0, 0) for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides, pads)
        return summed / counts
    return summed / float(np.prod(k))


@op("avg_pool1d")
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", ceil_mode,
                 exclusive)


@op("avg_pool2d")
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", ceil_mode,
                 exclusive, data_format == "NHWC")


@op("avg_pool3d")
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", ceil_mode,
                 exclusive, data_format == "NDHWC")


@op("max_pool1d")
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "max_pool1d(return_mask=True): use max_pool2d on a [N,C,1,L] "
            "view — 2d carries the argmax path")
    return _pool(x, kernel_size, stride, padding, 1, "max", ceil_mode)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _max_pool2d_with_index(x, kernel_size, stride, padding):
    """Pooled values + flat h*w argmax indices (phi `max_pool2d_with_index`
    role). Static small kernel → stacked shifted views + one argmax; XLA
    fuses the stack away."""
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride or kernel_size)
    ph, pw = _pair(padding)
    n, c, h, w = x.shape
    neg = jnp.finfo(jnp.float32).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    views = []
    for ki in range(kh):
        for kj in range(kw):
            views.append(jax.lax.slice(
                xp, (0, 0, ki, kj),
                (n, c, ki + (oh - 1) * sh + 1, kj + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
    patches = jnp.stack(views)                      # [kh*kw, N, C, OH, OW]
    local = jnp.argmax(patches, axis=0)             # [N, C, OH, OW]
    vals = jnp.max(patches, axis=0)
    ki = local // kw
    kj = local % kw
    gy = jnp.arange(oh)[None, None, :, None] * sh + ki - ph
    gx = jnp.arange(ow)[None, None, None, :] * sw + kj - pw
    mask = (gy.clip(0, h - 1) * w + gx.clip(0, w - 1)).astype(jnp.int32)
    return vals, mask


@op("max_pool2d")
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise NotImplementedError("return_mask needs NCHW")
        if ceil_mode:
            raise NotImplementedError("return_mask with ceil_mode")
        return _max_pool2d_with_index(x, kernel_size, stride, padding)
    return _pool(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                 channel_last=data_format == "NHWC")


@op("max_pool3d")
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                 channel_last=data_format == "NDHWC")


def _adaptive(x, output_size, n, mode):
    out_sz = _tup(output_size, n)
    spatial = x.shape[2:]
    out = x
    # decompose into per-axis windows when evenly divisible; general case uses
    # mean/max over index buckets
    if all(s % o == 0 for s, o in zip(spatial, out_sz)):
        k = tuple(s // o for s, o in zip(spatial, out_sz))
        window = (1, 1) + k
        if mode == "max":
            return jax.lax.reduce_window(x, -np.inf, jax.lax.max, window,
                                         window, "VALID")
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, window,
                                       "VALID")
        return summed / float(np.prod(k))
    # uneven: gather per output cell (small output sizes typical)
    for ax, o in enumerate(out_sz):
        dim = out.shape[2 + ax]
        starts = [int(np.floor(i * dim / o)) for i in range(o)]
        ends = [int(np.ceil((i + 1) * dim / o)) for i in range(o)]
        pieces = []
        for s_, e_ in zip(starts, ends):
            sl = [slice(None)] * out.ndim
            sl[2 + ax] = slice(s_, e_)
            seg = out[tuple(sl)]
            red = jnp.max(seg, axis=2 + ax, keepdims=True) if mode == "max" \
                else jnp.mean(seg, axis=2 + ax, keepdims=True)
            pieces.append(red)
        out = jnp.concatenate(pieces, axis=2 + ax)
    return out


@op("adaptive_avg_pool1d")
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


@op("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg")


@op("adaptive_avg_pool3d")
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg")


@op("adaptive_max_pool1d")
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


@op("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


@op("adaptive_max_pool3d")
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")


def _unpool(x, indices, spatial_shape):
    """Scatter pooled values back to flat spatial positions (phi `unpool` /
    `unpool3d` role; indices layout = flat index over the spatial dims)."""
    n, c = x.shape[0], x.shape[1]
    flat_len = 1
    for s in spatial_shape:
        flat_len *= s
    xv = x.reshape(n, c, -1)
    iv = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, flat_len), x.dtype)
    bidx = jnp.arange(n)[:, None, None]
    cidx = jnp.arange(c)[None, :, None]
    out = out.at[bidx, cidx, iv].set(xv)
    return out.reshape((n, c) + tuple(spatial_shape))


def _unpool_out_size(in_sp, kernel_size, stride, padding, ndim,
                     output_size):
    if output_size is not None:
        sp = tuple(output_size[-ndim:])
        return sp
    ks = (kernel_size,) * ndim if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * ndim if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * ndim if isinstance(padding, int) else tuple(padding)
    return tuple((i - 1) * s - 2 * p + k
                 for i, k, s, p in zip(in_sp, ks, st, pd))


@op("max_unpool1d")
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    sp = _unpool_out_size(x.shape[2:], kernel_size, stride, padding, 1,
                          output_size)
    return _unpool(x, indices, sp)


@op("max_unpool2d")
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    sp = _unpool_out_size(x.shape[2:], kernel_size, stride, padding, 2,
                          output_size)
    return _unpool(x, indices, sp)


@op("max_unpool3d")
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    sp = _unpool_out_size(x.shape[2:], kernel_size, stride, padding, 3,
                          output_size)
    return _unpool(x, indices, sp)


__all__ += ["max_unpool1d", "max_unpool2d", "max_unpool3d"]
