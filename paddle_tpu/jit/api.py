"""jit.to_static / save / load: graph capture to XLA.

Role parity: `paddle.jit.to_static` (python/paddle/jit/ — SOT bytecode capture
+ AST fallback + PirInterpreter execution) and `jit.save/load`.

TPU-first collapse (SURVEY §3.5 note): capture-by-tracing into one XLA
program replaces all three reference IRs. A decorated function/Layer traces
once per input signature; the compiled executable replays with zero Python
op dispatch. Autograd integration: in eager mode the whole compiled program
re-enters the op-dispatch gate as ONE op, so `loss.backward()` runs the
compiled VJP — the "same code runs eager and compiled" capability.

RNG under capture: the global generator key is threaded as an implicit
input/output of the traced program, so dropout stays correct and advances
state across replays (the reference needs its RNG-state tracker for this;
here it falls out of functional PRNG).
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import jax.numpy as jnp

from ..core import flags, rng
from ..core.dispatch import apply
from ..core.export_compat import get_jax_export
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import xla_cost as _xla_cost


def _compile_retry():
    """Retry policy for trace/compile builds: transient compile-path
    faults (remote-chip tunnel blips, injected jit.compile) retry with
    backoff before surfacing.  PADDLE_TPU_COMPILE_RETRIES tunes it."""
    from ..resilience.retry import env_policy

    return env_policy(
        "jit.compile", "PADDLE_TPU_COMPILE_RETRIES", 2,
        base_delay=0.05, max_delay=1.0,
        # deterministic user bugs (shape/type errors in the traced
        # fn) must not pay a second multi-second trace+compile
        give_up_on=(TypeError, ValueError, KeyError, AttributeError,
                    IndexError))


def _sig_of(x):
    if isinstance(x, Tensor):
        return ("T", tuple(x._value.shape), str(x._value.dtype))
    if isinstance(x, jax.Array):
        return ("A", tuple(x.shape), str(x.dtype))
    if isinstance(x, (list, tuple)):
        return tuple(_sig_of(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _sig_of(v)) for k, v in x.items()))
    return ("S", repr(x))


class StaticFunction:
    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, **kwargs):
        self._fn = function
        self._layer = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        elif hasattr(function, "__self__") and isinstance(
                function.__self__, Layer):
            self._layer = function.__self__
        self._cache = {}
        self._input_spec = input_spec
        functools.update_wrapper(self, self._fn)
        self._last_concrete = None

    @property
    def layer(self):
        return self._layer

    def _collect_state(self):
        if self._layer is None:
            return {}, {}
        return self._layer.functional_state()

    def _build(self, treedef, static_leaves, n_dyn, training):
        from ..resilience import faults as _faults

        # `jit.compile` fault point: the round-5 incident class (tunnel
        # window closed mid-compile) — the caller retries the build via
        # the jit.compile retry policy before raising
        _faults.fire("jit.compile",
                     fn=getattr(self._fn, "__name__", "fn"))
        from . import dy2static

        # AST tier: rewrite tensor-dependent if/while to lax.cond/while_loop
        # before tracing (reference dy2static transformers role); functions
        # without retrievable source trace as-is
        fn = dy2static.convert(self._fn)
        layer = self._layer

        def pure(params, buffers, key, *dyn_vals):
            leaves = list(static_leaves)
            it = iter(dyn_vals)
            leaves = [next(it) if l is _DYN else l for l in leaves]
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            old_key = rng.default_generator.get_state()
            rng.default_generator.set_state(key)
            def wrap_leaf(v):
                return Tensor(v) if isinstance(v, jax.Array) else v

            # wrap dynamic leaves in BOTH args and kwargs (kwarg tensors must
            # reach the user function as Tensors too)
            w_args, w_kwargs = jax.tree_util.tree_map(wrap_leaf, (args, kwargs))
            try:
                with flags.trace_guard():
                    if layer is not None:
                        with layer.bind_state(params, buffers) as (np_, nb_):
                            out = fn(*w_args, **w_kwargs)
                            new_buffers = {n: nb_[n]._value for n in nb_}
                    else:
                        out = fn(*w_args, **w_kwargs)
                        new_buffers = {}
                new_key = rng.default_generator.get_state()
            finally:
                rng.default_generator.set_state(old_key)

            out_vals = jax.tree_util.tree_map(
                lambda o: o._value if isinstance(o, Tensor) else o, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            return out_vals, new_buffers, new_key

        # compile-cost capture: with telemetry on, the first call per
        # signature AOT-compiles inside an `xla.compile:jit::<fn>` span
        # carrying cost_analysis FLOPs/bytes; with telemetry off (or
        # under an outer trace) this is a plain jit call
        return _xla_cost.instrument(
            jax.jit(pure),
            label=f"jit::{getattr(self._fn, '__name__', 'fn')}")

    def __call__(self, *args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        dyn_idx = [i for i, l in enumerate(leaves)
                   if isinstance(l, (Tensor, jax.Array))]
        static_leaves = [
            _DYN if i in dyn_idx else l for i, l in enumerate(leaves)]
        training = self._layer.training if self._layer is not None else True
        key = (tuple(_sig_of(leaves[i]) for i in dyn_idx),
               tuple((i, _sig_of(l)) for i, l in enumerate(static_leaves)
                     if l is not _DYN), training)
        compiled = self._cache.get(key)
        if compiled is None:
            # trace-cache telemetry: a miss past the first build is a
            # RETRACE — the silent recompile class the round-5 "44 ms
            # IDLE per step" hunt chased by hand.  Counted, and the
            # triggering signature lands in the flight recorder.
            _metrics.inc("jit.trace_cache.miss")
            if self._cache:
                _metrics.inc("jit.retrace")
                _flight.record(
                    "jit.retrace",
                    fn=getattr(self._fn, "__name__", "fn"),
                    n_cached=len(self._cache),
                    dyn_sig=repr(key[0])[:200])
            compiled = _compile_retry().call(
                self._build, treedef, static_leaves, len(dyn_idx),
                training)
            self._cache[key] = compiled
        else:
            _metrics.inc("jit.trace_cache.hit")
        self._last_concrete = (compiled, treedef, static_leaves, dyn_idx)

        params, buffers = self._collect_state()
        gen_key = rng.default_generator.get_state()

        param_tensors = dict(self._layer.named_parameters()) \
            if self._layer is not None else {}
        dyn_args = [leaves[i] for i in dyn_idx]

        def mega(params_t, buffers_v, key_v, *dyn):
            vals = [d for d in dyn]
            return compiled(params_t, buffers_v, key_v, *vals)

        # Route through the dispatch gate: one op covering the whole program,
        # so eager backward() differentiates through the compiled executable.
        out_vals, new_buffers, new_key = apply(
            f"jit::{getattr(self._fn, '__name__', 'fn')}",
            mega, param_tensors, buffers, gen_key, *dyn_args)

        new_key_val = new_key._value if isinstance(new_key, Tensor) \
            else new_key
        # under an outer trace (e.g. jit.save exporting a Layer whose
        # forward is already a StaticFunction) the threaded key is a
        # tracer — writing it into the global generator would leak it
        if not isinstance(new_key_val, jax.core.Tracer):
            rng.default_generator.set_state(new_key_val)
        if self._layer is not None and new_buffers:
            named_b = dict(self._layer.named_buffers())
            items = new_buffers.items() if isinstance(new_buffers, dict) else []
            for n, v in items:
                if n in named_b:
                    named_b[n]._value = v._value if isinstance(v, Tensor) else v
        return out_vals

    def concrete_program(self):
        return self._last_concrete


class _Dyn:
    __slots__ = ()

    def __repr__(self):
        return "<dyn>"


_DYN = _Dyn()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """backend=None (default): trace + AST dy2static into one XLA program.
    backend='sot': the SOT-role eager-capture tier (jit/sot/) — arbitrary
    Python incl. source-less functions, graph breaks at value forces,
    guarded branch cache (reference's default `to_static` tier)."""
    def decorate(fn):
        if backend in ("sot", "SOT"):
            from .sot import symbolic_translate

            if isinstance(fn, Layer):
                fn.forward = symbolic_translate(fn.forward)
                return fn
            return symbolic_translate(fn)
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer (or StaticFunction) for deployment: params +
    jax.export'd StableHLO program when an input_spec is given.

    Parity: `paddle.jit.save` (program + persistables); the exported artifact
    is the AOT analog of the saved ProgramDesc.
    """
    if input_spec is not None:
        get_jax_export()  # fail before writing partial artifacts
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..framework.io_utils import save as fsave

    target = layer
    if isinstance(layer, StaticFunction):
        target = layer.layer
    state = target.state_dict() if isinstance(target, Layer) else {}
    fsave(state, path + ".pdparams")

    exported_path = None
    if input_spec is not None and isinstance(target, Layer):
        params, buffers = target.functional_state()
        key = rng.default_generator.get_state()

        # if the Layer's forward was to_static-wrapped, export the original
        # forward — re-entering StaticFunction during export tracing would
        # thread the traced RNG key through the global generator
        fwd = target.forward
        call = fwd._fn if isinstance(fwd, StaticFunction) else target

        def pure(params, buffers, key, *dyn):
            with flags.trace_guard():
                with target.bind_state(params, buffers):
                    wrapped = [Tensor(v) for v in dyn]
                    out = call(*wrapped)
            return jax.tree_util.tree_map(
                lambda o: o._value if isinstance(o, Tensor) else o, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        specs = [
            jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
            if hasattr(s, "shape") else s for s in input_spec
        ]
        was_training = target.training
        target.eval()
        try:
            exp = get_jax_export().export(jax.jit(pure))(
                jax.tree_util.tree_map(
                    lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params),
                jax.tree_util.tree_map(
                    lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), buffers),
                jax.ShapeDtypeStruct(key.shape, key.dtype), *specs)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exp.serialize())
            exported_path = path + ".pdmodel"
        finally:
            if was_training:
                target.train()
    meta = {"exported": exported_path is not None,
            "class": type(target).__name__}
    if isinstance(target, Layer):
        meta["param_names"] = [n for n, _ in target.named_parameters()]
        meta["buffer_names"] = [n for n, _ in target.named_buffers()]
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """Deployment-side loaded model (parity: paddle.jit.TranslatedLayer /
    C++ jit::Layer)."""

    def __init__(self, exported, state, key, param_names=(), buffer_names=()):
        super().__init__()
        self._exported = exported
        self._state = state
        self._key = key
        self._param_names = list(param_names)
        self._buffer_names = list(buffer_names)

    def forward(self, *inputs):
        vals_of = {k: (v._value if isinstance(v, Tensor) else v)
                   for k, v in self._state.items()}
        p = {k: vals_of[k] for k in self._param_names if k in vals_of}
        b = {k: vals_of[k] for k in self._buffer_names if k in vals_of}
        vals = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        out = self._exported.call(p, b, self._key, *vals)
        return jax.tree_util.tree_map(Tensor, out)


def load(path, **configs):
    from ..framework.io_utils import load as fload

    state = fload(path + ".pdparams") if os.path.exists(path + ".pdparams") \
        else {}
    meta_path = path + ".pdmeta"
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
    if meta.get("exported") and os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            exp = get_jax_export().deserialize(bytearray(f.read()))
        return TranslatedLayer(exp, state, rng.default_generator.get_state(),
                               meta.get("param_names", ()),
                               meta.get("buffer_names", ()))
    raise FileNotFoundError(
        f"no exported program at {path}.pdmodel; load params with "
        f"paddle_tpu.load({path!r} + '.pdparams') instead")
