"""Dynamic-to-static control-flow conversion (AST tier).

Role parity: the reference's dy2static AST transformers
(`python/paddle/jit/dy2static/transformers/convert_operators.py`,
`ifelse_transformer.py`, `loop_transformer.py`) and the SOT fallback's
graph-break contract. TPU-first: instead of emitting `conditional_block` /
`while` ops into a ProgramDesc, tensor-dependent `if`/`while` become
`jax.lax.cond` / `jax.lax.while_loop` in the traced program — XLA-native
control flow, no second IR.

How it works:
  * `convert(fn)` rewrites the function's AST: every `if` whose outcome may
    depend on a traced Tensor becomes `_jst_if(pred, true_fn, false_fn,
    (threaded vars…))`; every `while` becomes `_jst_while(cond_fn, body_fn,
    (threaded vars…))`; `and`/`or`/`not` inside tests become
    `_jst_and/or/not` (tensor-aware, both operands evaluated).
  * At runtime the `_jst_*` helpers check the predicate: a concrete bool
    takes the plain Python path (eager mode — zero overhead beyond one
    isinstance); a traced Tensor routes through `lax.cond`/`while_loop`
    with the *Tensor-valued* threaded variables as carried state.
  * Variables assigned under a traced branch/loop must hold Tensors (or
    stay untouched): rebinding a Python scalar divergently is a
    graph-break and raises `Dy2StaticError` with guidance — the loud-error
    contract (VERDICT.md round-1 item 5) instead of silent specialization.

Scope: `if`/`while`/boolean ops at any nesting depth inside the converted
function; user-defined callees are converted transitively via `_jst_call`
(reference convert_call role). `for` over Python iterables stays Python
(it unrolls under trace, matching the reference's static-range behavior).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert", "Dy2StaticError"]


class Dy2StaticError(RuntimeError):
    pass


_HELPERS = "__jst__"
_conversion_cache: dict = {}


# =========================== runtime helpers ===========================

def _is_traced(x):
    return isinstance(x, Tensor) and isinstance(x._value, jax.core.Tracer)


def _tensor_bool(pred):
    """Concrete truthiness for non-traced predicates."""
    if isinstance(pred, Tensor):
        return bool(jax.device_get(pred._value))
    return bool(pred)


def _thread_split(vals):
    """Split threaded vars into (tensor positions, tensor values, template)."""
    tpos, tvals = [], []
    for i, v in enumerate(vals):
        if isinstance(v, Tensor):
            tpos.append(i)
            tvals.append(v._value)
    return tpos, tvals


def _thread_merge(vals, tpos, new_tvals):
    out = list(vals)
    for i, v in zip(tpos, new_tvals):
        out[i] = Tensor(v)
        out[i].stop_gradient = vals[i].stop_gradient \
            if isinstance(vals[i], Tensor) else True
    return tuple(out)


class _Undef:
    """Sentinel for threaded variables that were unbound before the
    control-flow statement (reference UndefinedVar role)."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()


def _jst_if(pred, true_fn, false_fn, names, vals):
    if not _is_traced(pred):
        return true_fn(*vals) if _tensor_bool(pred) else false_fn(*vals)

    tpos, tvals = _thread_split(vals)

    def run(branch_fn):
        def g(carried):
            merged = _thread_merge(vals, tpos, carried)
            outs = branch_fn(*merged)
            mask = tuple(isinstance(o, Tensor) for o in outs)
            out_tvals = tuple(o._value for o in outs if isinstance(o, Tensor))
            rest = tuple(o for o in outs if not isinstance(o, Tensor))
            return out_tvals, rest, mask
        return g

    # trace both branches once to validate cross-branch structure and
    # collect the (branch-invariant) non-Tensor outputs
    t_tvals, t_rest, t_mask = run(true_fn)(tuple(tvals))
    f_tvals, f_rest, f_mask = run(false_fn)(tuple(tvals))
    if t_mask != f_mask:
        diverging = [n for n, a, b in zip(names, t_mask, f_mask) if a != b]
        raise Dy2StaticError(
            f"dy2static: variables {diverging} are Tensors on one path of "
            "a traced `if` but not the other; assign every threaded "
            "variable a Tensor on both paths (e.g. initialize with "
            "paddle_tpu.to_tensor)")
    rest_names = [n for n, m in zip(names, t_mask) if not m]
    for n, tr_, fr_ in zip(rest_names, t_rest, f_rest):
        if tr_ is not fr_ and tr_ != fr_:
            raise Dy2StaticError(
                f"dy2static: Python variable '{n}' takes different values "
                "in the two branches of a traced `if`; only Tensors can be "
                "selected by lax.cond — make it a Tensor or hoist the "
                "assignment out of the data-dependent branch")

    out_tvals = jax.lax.cond(
        pred._value,
        lambda c: run(true_fn)(c)[0],
        lambda c: run(false_fn)(c)[0],
        tuple(tvals))
    outs = []
    ti = ri = 0
    for is_t in t_mask:
        if is_t:
            outs.append(Tensor(out_tvals[ti]))
            ti += 1
        else:
            outs.append(t_rest[ri])
            ri += 1
    return tuple(outs)


def _jst_while(cond_fn, body_fn, names, vals):
    probe = cond_fn(*vals)
    if not _is_traced(probe):
        while _tensor_bool(probe):
            vals = body_fn(*vals)
            probe = cond_fn(*vals)
        return vals

    # numeric Python scalars in the carried state lift to 0-d Tensors
    # (e.g. the start/step constants of a converted range-for); anything
    # else non-Tensor still fails loudly
    vals = tuple(
        Tensor(jnp.asarray(v)) if isinstance(v, (int, float, bool))
        else v for v in vals)
    tpos, tvals = _thread_split(vals)
    if len(tpos) != len(vals):
        non = [n for n, v in zip(names, vals) if not isinstance(v, Tensor)]
        raise Dy2StaticError(
            f"dy2static: traced `while` carries non-Tensor variables {non}; "
            "XLA while_loop state must be Tensors — convert them with "
            "paddle_tpu.to_tensor or hoist them out of the loop")

    def cond(carried):
        merged = _thread_merge(vals, tpos, carried)
        p = cond_fn(*merged)
        return p._value if isinstance(p, Tensor) else p

    def body(carried):
        merged = _thread_merge(vals, tpos, carried)
        outs = body_fn(*merged)
        for n, b, a in zip(names, merged, outs):
            if isinstance(b, Tensor) != isinstance(a, Tensor):
                raise Dy2StaticError(
                    f"dy2static: variable '{n}' switches between Tensor "
                    "and non-Tensor inside a traced `while` body; the "
                    "loop state must keep a fixed structure")
        _, out_tvals = _thread_split(outs)
        if len(out_tvals) != len(carried):
            raise Dy2StaticError(
                "dy2static: traced `while` body changed which variables "
                "hold Tensors; the loop state must keep a fixed structure")
        return tuple(out_tvals)

    out_tvals = jax.lax.while_loop(cond, body, tuple(tvals))
    return _thread_merge(vals, tpos, out_tvals)


def _jst_and(x, y):
    xv = x() if callable(x) else x
    if isinstance(xv, Tensor) and _is_traced(xv):
        yv = y() if callable(y) else y
        yvv = yv._value if isinstance(yv, Tensor) else yv
        return Tensor(jnp.logical_and(xv._value.astype(bool),
                                      jnp.asarray(yvv).astype(bool)))
    if not _tensor_bool(xv):
        return xv if not isinstance(xv, Tensor) else False
    return y() if callable(y) else y


def _jst_or(x, y):
    xv = x() if callable(x) else x
    if isinstance(xv, Tensor) and _is_traced(xv):
        yv = y() if callable(y) else y
        yvv = yv._value if isinstance(yv, Tensor) else yv
        return Tensor(jnp.logical_or(xv._value.astype(bool),
                                     jnp.asarray(yvv).astype(bool)))
    if _tensor_bool(xv):
        return xv if not isinstance(xv, Tensor) else True
    return y() if callable(y) else y


def _jst_not(x):
    if isinstance(x, Tensor) and _is_traced(x):
        return Tensor(jnp.logical_not(x._value.astype(bool)))
    return not _tensor_bool(x)


def _jst_call(fn):
    """Transitive conversion of user callees (reference convert_call)."""
    from ..nn.layer_base import Layer

    if isinstance(fn, Layer) or not callable(fn):
        return fn  # Layer.forward goes through __call__; convert on demand
    mod = getattr(fn, "__module__", None) or ""
    if mod.split(".")[0] in ("paddle_tpu", "jax", "jaxlib", "numpy",
                             "builtins", "math", "functools"):
        return fn
    if isinstance(fn, (types.FunctionType, types.MethodType)):
        try:
            return convert(fn)
        except Dy2StaticError:
            raise  # loud-error contract: never silently unconvert a callee
        except Exception:
            return fn
    return fn


def _jst_for_iter(thunk):
    """Evaluate a `for` loop's iterable; tensor-dependent trip counts
    (e.g. `range(t)` with traced `t`) fail LOUDLY instead of surfacing a
    deep tracer error or silently specializing (reference: SOT converts
    these; the AST tier's contract is convert-or-raise)."""
    try:
        it = thunk()
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.TracerBoolConversionError,
            jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError) as e:
        raise Dy2StaticError(
            "dy2static: `for` over a tensor-dependent range cannot be "
            "converted to XLA control flow. Use a Python-int bound, "
            "vectorize with paddle_tpu.arange + masked ops, or express "
            "the loop as `while` (converted to lax.while_loop).") from e
    if _is_traced(it) and getattr(it, "ndim", 1) == 0:
        raise Dy2StaticError(
            "dy2static: `for` over a 0-d traced tensor is not iterable; "
            "use a Python int or a convertible `while` loop.")
    return it


class _Helpers:
    if_ = staticmethod(_jst_if)
    while_ = staticmethod(_jst_while)
    and_ = staticmethod(_jst_and)
    or_ = staticmethod(_jst_or)
    not_ = staticmethod(_jst_not)
    call = staticmethod(_jst_call)
    for_iter = staticmethod(_jst_for_iter)
    UNDEF = UNDEF


# =========================== AST transform ===========================

def _assigned_names(nodes):
    out = set()
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                out.add(n.id)
            elif isinstance(n, ast.AugAssign) and isinstance(
                    n.target, ast.Name):
                out.add(n.target.id)
    return out


def _read_names(node):
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _has_return(nodes):
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.Return):
                return True
    return False


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _name(self, base):
        self._uid += 1
        return f"__jst_{base}_{self._uid}"

    @staticmethod
    def _undef_guards(names):
        """`try: name \nexcept (NameError, UnboundLocalError): name = UNDEF`
        per threaded name — branches may bind vars that don't exist yet."""
        guards = []
        for m in names:
            guards.append(ast.Try(
                body=[ast.Expr(value=ast.Name(id=m, ctx=ast.Load()))],
                handlers=[ast.ExceptHandler(
                    type=ast.Tuple(
                        elts=[ast.Name(id="NameError", ctx=ast.Load()),
                              ast.Name(id="UnboundLocalError",
                                       ctx=ast.Load())],
                        ctx=ast.Load()),
                    name=None,
                    body=[ast.Assign(
                        targets=[ast.Name(id=m, ctx=ast.Store())],
                        value=ast.Attribute(
                            value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                            attr="UNDEF", ctx=ast.Load()))])],
                orelse=[], finalbody=[]))
        return guards

    # ---- boolean ops in any expression ----
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "and_" if isinstance(node.op, ast.And) else "or_"
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                    attr=op, ctx=ast.Load()),
                args=[expr, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                       kw_defaults=[], defaults=[]),
                    body=rhs)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                    attr="not_", ctx=ast.Load()),
                args=[node.operand], keywords=[]), node)
        return node

    # ---- calls: transitive conversion ----
    def visit_Call(self, node):
        self.generic_visit(node)
        node.func = ast.Call(
            func=ast.Attribute(
                value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                attr="call", ctx=ast.Load()),
            args=[node.func], keywords=[])
        return node

    # ---- for: stays a Python loop (static unroll), but the iterable is
    # routed through for_iter so tensor-dependent ranges raise loudly ----
    def visit_For(self, node):
        # `for i in range(...)` with a simple Name target and no
        # break/continue/else rewrites to a while loop BEFORE visiting —
        # the while converter then handles tensor-dependent bounds via
        # lax.while_loop (reference dy2static/transformers loop
        # conversion). Everything else stays a Python loop (static
        # unroll) with a loud for_iter guard on the iterable.
        if self._is_rangefor(node):
            return self._rangefor_to_while(node)
        self.generic_visit(node)
        node.iter = ast.Call(
            func=ast.Attribute(
                value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                attr="for_iter", ctx=ast.Load()),
            args=[ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=node.iter)],
            keywords=[])
        ast.fix_missing_locations(node)
        return node

    @staticmethod
    def _is_rangefor(node):
        if node.orelse or not isinstance(node.target, ast.Name):
            return False
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            return False
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Break, ast.Continue)):
                return False
            if sub is not node and isinstance(
                    sub, (ast.For, ast.While, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
                # nested loops/functions may own the break — keep simple,
                # only flat range-for bodies convert
                if any(isinstance(s, (ast.Break, ast.Continue))
                       for s in ast.walk(sub)):
                    return False
        return True

    def _rangefor_to_while(self, node):
        if node.target.id == "_":
            # `_` is excluded from while-state threading (scratch-var
            # convention); rename the loop counter so it threads
            fresh = self._name("i")

            class _Ren(ast.NodeTransformer):
                def visit_Name(self, n):
                    if n.id == "_":
                        n.id = fresh
                    return n

            node.target = ast.Name(id=fresh, ctx=ast.Store())
            node.body = [_Ren().visit(b) for b in node.body]
        args = node.iter.args
        if len(args) == 1:
            start, stop, step = ast.Constant(value=0), args[0], \
                ast.Constant(value=1)
        elif len(args) == 2:
            start, stop, step = args[0], args[1], ast.Constant(value=1)
        else:
            start, stop, step = args
        i = node.target.id
        stop_n, step_n = self._name("stop"), self._name("step")
        pre = [
            ast.Assign(targets=[ast.Name(id=stop_n, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_n, ctx=ast.Store())],
                       value=step),
            ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                       value=start),
        ]
        # condition: step > 0 ? i < stop : i > stop — as arithmetic the
        # while converter can trace: (step>0 and i<stop) or (step<0 and
        # i>stop); BoolOps get converted by visit_BoolOp downstream
        cond = ast.BoolOp(op=ast.Or(), values=[
            ast.BoolOp(op=ast.And(), values=[
                ast.Compare(left=ast.Name(id=step_n, ctx=ast.Load()),
                            ops=[ast.Gt()],
                            comparators=[ast.Constant(value=0)]),
                ast.Compare(left=ast.Name(id=i, ctx=ast.Load()),
                            ops=[ast.Lt()],
                            comparators=[ast.Name(id=stop_n,
                                                  ctx=ast.Load())]),
            ]),
            ast.BoolOp(op=ast.And(), values=[
                ast.Compare(left=ast.Name(id=step_n, ctx=ast.Load()),
                            ops=[ast.Lt()],
                            comparators=[ast.Constant(value=0)]),
                ast.Compare(left=ast.Name(id=i, ctx=ast.Load()),
                            ops=[ast.Gt()],
                            comparators=[ast.Name(id=stop_n,
                                                  ctx=ast.Load())]),
            ]),
        ])
        incr = ast.Assign(
            targets=[ast.Name(id=i, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=i, ctx=ast.Load()),
                            op=ast.Add(),
                            right=ast.Name(id=step_n, ctx=ast.Load())))
        wl = ast.While(test=cond, body=list(node.body) + [incr], orelse=[])
        out = []
        for n in pre:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
            out.append(self.visit(n) or n)
        ast.copy_location(wl, node)
        ast.fix_missing_locations(wl)
        converted = self.visit(wl)
        if isinstance(converted, list):
            out.extend(converted)
        else:
            out.append(converted)
        return out

    # ---- if/while ----
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_return(node.body) or _has_return(node.orelse):
            # branch with `return` can't become lax.cond — leave as Python
            # (fails loudly at trace time if the predicate is traced)
            return node
        mod = sorted((_assigned_names(node.body)
                      | _assigned_names(node.orelse))
                     - {"_", _HELPERS})
        if not mod:
            return node
        tname, fname = self._name("true"), self._name("false")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=m) for m in mod],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=m, ctx=ast.Load()) for m in mod],
            ctx=ast.Load()))
        t_def = ast.FunctionDef(
            name=tname, args=args, body=list(node.body) + [ret],
            decorator_list=[], returns=None, type_params=[])
        f_def = ast.FunctionDef(
            name=fname, args=args, body=list(node.orelse) + [ret],
            decorator_list=[], returns=None, type_params=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=m, ctx=ast.Store()) for m in mod],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                    attr="if_", ctx=ast.Load()),
                args=[
                    node.test,
                    ast.Name(id=tname, ctx=ast.Load()),
                    ast.Name(id=fname, ctx=ast.Load()),
                    ast.Tuple(elts=[ast.Constant(value=m) for m in mod],
                              ctx=ast.Load()),
                    ast.Tuple(elts=[ast.Name(id=m, ctx=ast.Load())
                                    for m in mod], ctx=ast.Load()),
                ],
                keywords=[]))
        out = self._undef_guards(mod) + [t_def, f_def, assign]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_return(node.body) or node.orelse:
            return node
        mod = sorted((_assigned_names(node.body) | _read_names(node.test))
                     - {"_", _HELPERS})
        if not mod:
            return node
        cname, bname = self._name("cond"), self._name("body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=m) for m in mod],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        c_def = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=m, ctx=ast.Load()) for m in mod],
            ctx=ast.Load()))
        b_def = ast.FunctionDef(
            name=bname, args=args, body=list(node.body) + [ret],
            decorator_list=[], returns=None, type_params=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=m, ctx=ast.Store()) for m in mod],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_HELPERS, ctx=ast.Load()),
                    attr="while_", ctx=ast.Load()),
                args=[
                    ast.Name(id=cname, ctx=ast.Load()),
                    ast.Name(id=bname, ctx=ast.Load()),
                    ast.Tuple(elts=[ast.Constant(value=m) for m in mod],
                              ctx=ast.Load()),
                    ast.Tuple(elts=[ast.Name(id=m, ctx=ast.Load())
                                    for m in mod], ctx=ast.Load()),
                ],
                keywords=[]))
        out = self._undef_guards(mod) + [c_def, b_def, assign]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out


def convert(fn):
    """Return `fn` with tensor-dependent control flow rewritten to XLA
    control-flow primitives. Functions without source (builtins, C
    extensions) are returned unchanged."""
    cached = _conversion_cache.get(fn)
    if cached is not None:
        return cached

    bound_self = None
    raw = fn
    if isinstance(fn, types.MethodType):
        bound_self = fn.__self__
        raw = fn.__func__
    try:
        src = textwrap.dedent(inspect.getsource(raw))
    except (OSError, TypeError):
        _conversion_cache[fn] = fn
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        _conversion_cache[fn] = fn
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        _conversion_cache[fn] = fn
        return fn
    fdef.decorator_list = []  # run the body, not the decorators, again

    transformer = _ControlFlowTransformer()
    tree = transformer.visit(tree)
    ast.fix_missing_locations(tree)

    glb = dict(raw.__globals__)
    glb[_HELPERS] = _Helpers
    fname = f"<dy2static {raw.__qualname__}>"
    ns: dict = {}
    free = raw.__code__.co_freevars
    if free and raw.__closure__:
        # Closure conversion (VERDICT r2 task 6): compile the converted
        # body nested in a wrapper whose params shadow the free names, so
        # the inner code object gets real co_freevars again; then rebind
        # it to the ORIGINAL cells with types.FunctionType — `nonlocal`
        # mutation stays visible both ways, exactly like the source fn.
        outer_name = "__dy2s_outer__"
        outer = ast.FunctionDef(
            name=outer_name,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=n) for n in free],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fdef,
                  ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))],
            decorator_list=[], returns=None, type_params=[])
        mod_ast = ast.Module(body=[outer], type_ignores=[])
        ast.fix_missing_locations(mod_ast)
        exec(compile(mod_ast, filename=fname, mode="exec"), glb, ns)
        template = ns[outer_name](*[None] * len(free))
        cellmap = dict(zip(free, raw.__closure__))
        missing = [n for n in template.__code__.co_freevars
                   if n not in cellmap]
        if missing:
            raise Dy2StaticError(
                f"dy2static: converted {raw.__qualname__} references free "
                f"variables {missing} absent from the original closure")
        new_fn = types.FunctionType(
            template.__code__, glb, raw.__name__, raw.__defaults__,
            tuple(cellmap[n] for n in template.__code__.co_freevars))
        new_fn.__kwdefaults__ = raw.__kwdefaults__
    else:
        exec(compile(tree, filename=fname, mode="exec"), glb, ns)
        new_fn = ns[fdef.name]
    new_fn = functools.wraps(raw)(new_fn)
    if bound_self is not None:
        new_fn = types.MethodType(new_fn, bound_self)
    _conversion_cache[fn] = new_fn
    return new_fn
