"""Eager-capture engine behind paddle_tpu.jit.sot (see package docstring).

Data model
----------
Capture interprets one call eagerly, producing a flat trace:
  * op records   — (name, raw_fn, leafspec, treedef, n_out, out_refs):
                   one dispatched op; leafspec tags each flattened arg
                   leaf as a prior SSA value ("ref"), an implicit input
                   ("imp": a live Tensor outside the trace, e.g. a layer
                   parameter — re-read at every replay so optimizer steps
                   stay visible), a PRNG key ("rng": re-derived per call),
                   or a Python literal ("py").
  * force events — a Tensor left tensor-land via bool/int/float/item/
                   numpy/tolist; ends the current segment, keys a branch.
The trace then splits into segments at force events; each segment becomes
one jitted replay function whose outputs are the SSA values still live
downstream (+ the forced value). Chains are cached in a trie keyed by
(input signature) then (force outcomes), reference guard+cache role.
"""
from __future__ import annotations

import functools
import hashlib
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch, rng
from ...core.tensor import Tensor

MAX_PATHS_PER_SIG = 64
# On branch-table overflow the whole trie for that signature is evicted
# and recaptured (bounded memory, hot paths recompile); only after this
# many evictions does the signature fall back to eager permanently —
# a function forcing continuous data (float(loss) > t) degrades to
# capture-per-call then eager instead of silently pinning 64 stale paths.
MAX_TRIE_RESETS = 3

_RECAPTURE = object()  # _replay sentinel: guard miss / unseen branch


class SOTError(RuntimeError):
    pass


_dummy = None


def _dummy_key():
    """Shared placeholder key Tensor for RNG-free segments."""
    global _dummy
    if _dummy is None:
        _dummy = Tensor(jnp.zeros((), jnp.uint32), stop_gradient=True)
    return _dummy


def _is_prng_key(x):
    try:
        return jnp.issubdtype(x.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def _digest(value: np.ndarray):
    # fixed-size content key: raw tobytes in a trie key would hold the
    # whole array alive per branch and grow memory without bound for
    # large forced arrays (round-3 ADVICE)
    return hashlib.sha1(value.tobytes()).digest()


def _sig_of(x):
    if isinstance(x, Tensor):
        return ("T", tuple(x._value.shape), str(x._value.dtype))
    if isinstance(x, jax.Array):
        return ("A", tuple(x.shape), str(x.dtype))
    if isinstance(x, (list, tuple)):
        return (type(x).__name__,) + tuple(_sig_of(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _sig_of(v)) for k, v in x.items()))
    if isinstance(x, np.ndarray):
        return ("N", x.shape, str(x.dtype), _digest(x))
    return ("S", repr(x))


def _outcome_key(kind, value):
    """Hashable branch-table key for a forced value."""
    if isinstance(value, np.ndarray):
        return (kind, value.shape, str(value.dtype), _digest(value))
    if isinstance(value, (list, tuple)):
        return (kind, repr(value))
    return (kind, value)


# =========================== trace recording ===========================

class _Trace:
    """Flat eager trace of one call: op records and force events."""

    def __init__(self):
        self.events = []          # ("op", rec) | ("force", kind, ref, out)
        self.env = {}             # id(Tensor | jax.Array) -> ssa ref
        self.keepalive = []       # objects backing env ids (id-reuse guard)
        self.implicit = {}        # ssa ref -> Tensor/array read from outside
        self.n_refs = 0
        self.n_rng = 0

    def new_ref(self):
        r = self.n_refs
        self.n_refs += 1
        return r

    def bind(self, t):
        r = self.new_ref()
        self.env[id(t)] = r
        self.keepalive.append(t)
        return r

    def ref_of(self, t):
        r = self.env.get(id(t))
        if r is None:
            # first sight of an external value (parameter, module-level
            # constant): becomes a live-read input of the segment using it
            r = self.bind(t)
            self.implicit[r] = t
        return r

    # ---- dispatch hook ----
    def on_op(self, name, fn, args, kwargs, out):
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        spec = []
        for l in leaves:
            if isinstance(l, rng.OpKey) or (
                    isinstance(l, jax.Array) and _is_prng_key(l)):
                spec.append(("rng", self.n_rng))
                self.n_rng += 1
            elif isinstance(l, (Tensor, jax.Array)):
                # raw jax.Array args ride as refs, not baked literals —
                # a literal would silently replay a stale value for a
                # same-shaped array (the entry signature guards arrays by
                # shape/dtype only)
                spec.append(("ref", self.ref_of(l)))
            else:
                spec.append(("py", l))
        # dispatch wraps every output leaf into a Tensor (_wrap_outputs),
        # so the flattened output is all-Tensor, in replay order
        out_leaves = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))[0]
        out_refs = [self.bind(o) for o in out_leaves
                    if isinstance(o, Tensor)]
        self.events.append(
            ("op", (name, fn, tuple(spec), treedef, out_refs)))

    def on_force(self, t: Tensor, kind, value):
        # only tensors that belong to the trace key a branch; forcing an
        # unrelated eager tensor (e.g. a global counter) is not a break
        r = self.env.get(id(t))
        if r is None:
            return
        self.events.append(("force", kind, r, value))


_active = threading.local()


def _thread_local_on_op(name, fn, args, kwargs, out):
    """Routes the global dispatch hook to THIS thread's trace only: the
    hook slots are process-global, but capture is a per-thread activity —
    ops dispatched concurrently by other threads (prefetch workers,
    metrics) must not leak into the capturing thread's trace."""
    trace = getattr(_active, "trace", None)
    if trace is not None:
        trace.on_op(name, fn, args, kwargs, out)


def _thread_local_on_force(t, kind, value):
    trace = getattr(_active, "trace", None)
    if trace is not None:
        trace.on_force(t, kind, value)


_scope_lock = threading.Lock()
_n_scopes = 0


class _CaptureScope:
    def __init__(self, trace):
        self.trace = trace

    def __enter__(self):
        global _n_scopes
        if getattr(_active, "trace", None) is not None:
            raise SOTError("sot: nested capture is not supported")
        _active.trace = self.trace
        with _scope_lock:
            _n_scopes += 1
            dispatch.set_sot_recorder(_thread_local_on_op)
            Tensor._set_force_hook(_thread_local_on_force)
        return self.trace

    def __exit__(self, *exc):
        global _n_scopes
        _active.trace = None
        with _scope_lock:
            _n_scopes -= 1
            if _n_scopes == 0:
                dispatch.set_sot_recorder(None)
                Tensor._set_force_hook(None)


# =========================== segment build ===========================

class _Segment:
    """One jitted replay unit between graph breaks. `implicit` maps the
    refs this segment is responsible for binding at replay time to their
    live external objects — PER SEGMENT, because divergent branch suffixes
    allocate overlapping ref numbers for different external tensors (an
    entry-level map would let one branch clobber another's bindings)."""

    __slots__ = ("ops", "in_refs", "out_refs", "n_rng", "implicit",
                 "compiled")

    def __init__(self, ops, in_refs, out_refs, n_rng, implicit):
        self.ops = ops
        self.in_refs = tuple(in_refs)
        self.out_refs = tuple(out_refs)
        self.n_rng = n_rng
        self.implicit = implicit  # ref -> (obj, (shape, dtype))

        def replay(key, *vals):
            env = dict(zip(self.in_refs, vals))
            for name, fn, spec, treedef, orefs in self.ops:
                leaves = []
                for tag, payload in spec:
                    if tag == "ref":
                        leaves.append(env[payload])
                    elif tag == "rng":
                        leaves.append(
                            rng.OpKey(jax.random.fold_in(key, payload)))
                    else:
                        leaves.append(payload)
                a, kw = jax.tree_util.tree_unflatten(treedef, leaves)
                out = fn(*a, **kw)
                # dispatch wrapped every output leaf at capture time, so
                # orefs covers ALL flattened leaves, in order
                outs = jax.tree_util.tree_flatten(out)[0]
                for r, v in zip(orefs, outs):
                    env[r] = v
            return tuple(env[r] for r in self.out_refs)

        self.compiled = jax.jit(replay)


class _Node:
    """Chain node: a segment plus either a terminal output template or a
    branch table keyed by the forced outcome."""

    __slots__ = ("segment", "break_kind", "break_ref", "branches",
                 "out_template")

    def __init__(self, segment):
        self.segment = segment
        self.break_kind = None
        self.break_ref = None
        self.branches = {}
        self.out_template = None  # (treedef, leafspec) for terminal nodes


def _live_after(events, idx, final_refs):
    """Refs read by any event at/after position idx, plus final outputs."""
    live = set(final_refs)
    for ev in events[idx:]:
        if ev[0] == "op":
            for tag, payload in ev[1][2]:
                if tag == "ref":
                    live.add(payload)
        else:
            live.add(ev[2])
    return live


def _build_chain(trace, out_treedef, out_leafspec, final_refs):
    """Split the flat trace into a linked chain of nodes; returns the head."""
    events = trace.events
    seg_ops = []
    claimed = set()  # implicit refs already bound by an earlier segment

    def _sig_of_obj(t):
        v = t._value if isinstance(t, Tensor) else t
        return (tuple(v.shape), str(v.dtype))

    def close_segment(end_idx, break_ref=None, extra_needs=()):
        # inputs: refs used by this segment's ops that it didn't produce
        used = set()
        internal = set()
        for name, fn, spec, treedef, orefs in seg_ops:
            for tag, payload in spec:
                if tag == "ref" and payload not in internal:
                    used.add(payload)
            internal.update(orefs)
        live = _live_after(events, end_idx, final_refs)
        outs = sorted((internal & live) | ({break_ref} if break_ref is not
                                          None and break_ref in internal
                                          else set()))
        n_rng = sum(1 for (_, _, spec, _, _) in seg_ops
                    for tag, _ in spec if tag == "rng")
        implicit = {}
        # claim implicit refs this segment's ops read, PLUS any the replay
        # walker needs right after this segment (its break predicate; for
        # the terminal segment, output-template refs): an external tensor
        # returned untouched is in no op's arg list but must still bind
        for r in list(used) + list(extra_needs):
            if r in trace.implicit and r not in claimed:
                implicit[r] = (trace.implicit[r],
                               _sig_of_obj(trace.implicit[r]))
                claimed.add(r)
        return _Segment(list(seg_ops), sorted(used), outs, n_rng, implicit)

    head = None
    prev = None
    prev_outcome = None
    for i, ev in enumerate(events):
        if ev[0] == "op":
            seg_ops.append(ev[1])
        else:
            _, kind, ref, value = ev
            node = _Node(close_segment(i + 1, break_ref=ref,
                                       extra_needs=(ref,)))
            node.break_kind = kind
            node.break_ref = ref
            seg_ops = []
            if prev is None:
                head = node
            else:
                prev.branches[prev_outcome] = node
            prev = node
            prev_outcome = _outcome_key(kind, value)
    # terminal node
    node = _Node(close_segment(len(events), extra_needs=final_refs))
    node.out_template = (out_treedef, out_leafspec)
    if prev is None:
        head = node
    else:
        prev.branches[prev_outcome] = node
    return head


# =========================== the callable ===========================

class SOTFunction:
    """Captured function: guarded chain cache + eager re-capture."""

    def __init__(self, fn):
        self._fn = fn
        self._entries = {}   # sig -> {"head": _Node, "paths": int,
                             #         "implicit": {ref: Tensor}}
        self._trie_resets = {}  # sig -> eviction count (overflow policy)
        functools.update_wrapper(self, fn)

    # ---- capture ----
    def _capture(self, args, kwargs, sig):
        trace = _Trace()
        # bind explicit tensor/array inputs before running (raw jax.Arrays
        # are dynamic inputs too — see on_op)
        in_leaves = [
            l for l in jax.tree_util.tree_flatten(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))[0]
            if isinstance(l, (Tensor, jax.Array))
            and not isinstance(l, rng.OpKey) and not (
                isinstance(l, jax.Array) and _is_prng_key(l))]
        for l in in_leaves:
            trace.bind(l)
        with _CaptureScope(trace):
            out = self._fn(*args, **kwargs)
        out_leaves, out_treedef = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        out_spec = []
        final_refs = []
        for l in out_leaves:
            if isinstance(l, (Tensor, jax.Array)):
                r = trace.ref_of(l)  # binds if created outside dispatch
                out_spec.append(("ref", r))
                final_refs.append(r)
            else:
                out_spec.append(("py", l))
        head = _build_chain(trace, out_treedef, out_spec, final_refs)

        entry = self._entries.get(sig)
        if entry is None:
            self._entries[sig] = {
                "head": head, "paths": 1,
                "in_refs": [trace.env[id(l)] for l in in_leaves],
            }
        else:
            self._merge(entry, head)
        return out

    @staticmethod
    def _merge(entry, new_head):
        """Graft the new path into the existing trie at the first unseen
        branch outcome (segments before it are identical by construction:
        same ops ran, same forces occurred)."""
        cur, new = entry["head"], new_head
        while True:
            if new.out_template is not None or cur.out_template is not None:
                return  # identical terminal path — nothing to graft
            (outcome, nxt), = ((o, n) for o, n in new.branches.items())
            if outcome in cur.branches:
                cur, new = cur.branches[outcome], nxt
            else:
                cur.branches[outcome] = nxt
                entry["paths"] += 1
                return

    # ---- replay ----
    def _replay(self, sig, entry, args, kwargs):
        in_leaves = [
            l for l in jax.tree_util.tree_flatten(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))[0]
            if isinstance(l, (Tensor, jax.Array))
            and not isinstance(l, rng.OpKey) and not (
                isinstance(l, jax.Array) and _is_prng_key(l))]
        values = dict(zip(entry["in_refs"], in_leaves))
        node = entry["head"]
        while True:
            seg = node.segment
            for r, (t, expect) in seg.implicit.items():
                # live read: the same external Tensor (e.g. a parameter)
                # with its CURRENT value; shape/dtype guard against drift
                v = t._value if isinstance(t, Tensor) else t
                if (tuple(v.shape), str(v.dtype)) != expect:
                    self._entries.pop(sig, None)
                    return _RECAPTURE
                values[r] = t
            ins = [values[r] for r in seg.in_refs]
            key = Tensor(rng.default_generator.split(), stop_gradient=True) \
                if seg.n_rng else _dummy_key()
            outs = dispatch.apply(
                f"sot_segment[{self._fn.__name__}]", seg.compiled,
                key, *ins)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for r, t in zip(seg.out_refs, outs):
                values[r] = t
            if node.out_template is not None:
                treedef, spec = node.out_template
                leaves = [values[p] if tag == "ref" else p
                          for tag, p in spec]
                return jax.tree_util.tree_unflatten(treedef, leaves)
            forced = values[node.break_ref]
            raw = np.asarray(forced._value)
            if node.break_kind == "bool":
                outcome = _outcome_key("bool", bool(raw))
            elif node.break_kind == "int":
                outcome = _outcome_key("int", int(raw))
            elif node.break_kind == "float":
                outcome = _outcome_key("float", float(raw))
            else:
                outcome = _outcome_key(node.break_kind, raw)
            nxt = node.branches.get(outcome)
            if nxt is None:
                return _RECAPTURE  # unseen branch — caller recaptures
            node = nxt

    def __call__(self, *args, **kwargs):
        if getattr(_active, "trace", None) is not None:
            # nested SOT call inside a capture: inline it (record its ops
            # into the outer trace)
            return self._fn(*args, **kwargs)
        from ...core import flags

        if flags.in_static_mode() or flags.in_trace():
            # static recording / an enclosing functional trace owns the
            # program — SOT's eager capture machinery would record nothing
            return self._fn(*args, **kwargs)

        sig = (_sig_of(args), _sig_of(kwargs))
        entry = self._entries.get(sig)
        if entry is not None:
            if entry["paths"] >= MAX_PATHS_PER_SIG:
                resets = self._trie_resets.get(sig, 0)
                if resets >= MAX_TRIE_RESETS:
                    # repeated overflow: the function branches on
                    # continuous data — permanently eager for this sig
                    warnings.warn(
                        f"sot: {self._fn.__name__} exceeded "
                        f"{MAX_PATHS_PER_SIG} traced branch paths "
                        f"{resets + 1}x for one signature (likely a "
                        "predicate on continuous data, e.g. "
                        "float(x) > t); falling back to eager — "
                        "restructure with lax.cond/jnp.where or move the "
                        "predicate out of the captured function",
                        stacklevel=2)
                    return self._fn(*args, **kwargs)
                # evict the trie and recapture: bounded memory, hot
                # paths rebuild; beats pinning 64 stale paths forever
                self._trie_resets[sig] = resets + 1
                self._entries.pop(sig, None)
                warnings.warn(
                    f"sot: {self._fn.__name__} exceeded "
                    f"{MAX_PATHS_PER_SIG} traced branch paths; evicting "
                    f"the cached trie for this signature "
                    f"(reset {resets + 1}/{MAX_TRIE_RESETS})",
                    stacklevel=2)
                return self._capture(args, kwargs, sig)
            out = self._replay(sig, entry, args, kwargs)
            if out is not _RECAPTURE:
                return out
        return self._capture(args, kwargs, sig)


def symbolic_translate(fn):
    """Reference `paddle.jit.sot.translate.symbolic_translate` name."""
    if isinstance(fn, SOTFunction):
        return fn
    return SOTFunction(fn)


sot_capture = symbolic_translate
