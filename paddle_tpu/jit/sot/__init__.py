"""SOT-role graph capture: arbitrary-Python capture with graph breaks.

Role parity: `python/paddle/jit/sot/` — the reference's default `to_static`
tier captures arbitrary Python through a CPython eval-frame hook +
symbolic bytecode interpreter (`opcode_translator/executor/
opcode_executor.py`), emitting StatementIR subgraphs separated by graph
breaks, guarded and cached per input signature.

TPU-first redesign (not a bytecode port): the eval-frame/bytecode
machinery exists because the reference must intercept ops *without
executing them*. Here every op already flows through one dispatch gate
(`core/dispatch.py`), so capture runs the function EAGERLY once per
(signature, branch-path) and records each dispatched op into an SSA list
— arbitrary Python (closures, comprehensions, dict flow, functions with
no retrievable source — the AST tier's blind spot) just executes, no
interpreter needed. What the bytecode tier calls a graph break surfaces
here as a *force point*: `bool()/int()/float()/item()/numpy()` on a
Tensor. Each force ends the current segment, the forced value becomes a
segment output, and the concrete outcome keys a branch table to the next
segment — exactly the reference's subgraph + guard + cache structure
(`sot/opcode_translator/executor/guard.py` role), with re-capture on an
unseen outcome instead of re-translation.

Execution: each segment replays as one jitted pure function dispatched as
ONE framework op, so eager autograd composes across segments and graph
breaks (the reference runs its subgraphs through partial_program the same
way). Randomness: PRNG keys recorded in op args are re-derived from a
per-call key threaded into every segment, so dropout resamples across
replays instead of baking the capture-time mask.

Contract (matches tests/test_sot.py's adversarial section):

* Graph breaks / branch points are EXACTLY the force set: ``bool()``,
  ``int()``, ``float()``, ``.item()``, ``.numpy()``, ``.tolist()`` on a
  trace Tensor. Each concrete outcome keys one cached path (ndarray
  outcomes by sha1 digest, so trie memory is O(paths)).
* Non-tensor side effects (prints, container mutation, global counters)
  execute at CAPTURE only and are skipped on replay — the jax.jit
  contract. Tensor dataflow through mutated containers stays correct
  (ops are recorded SSA, the container surgery is capture-time Python).
* Non-tensor Python values (closures, literals, config) are baked per
  input signature; tensors/arrays guard by shape/dtype only. Changing a
  baked value without changing the signature replays the stale capture.
* Branch-table overflow (``MAX_PATHS_PER_SIG`` outcomes for one
  signature — e.g. a predicate on continuous data like
  ``float(loss) > t``): the trie is evicted and recaptured up to
  ``MAX_TRIE_RESETS`` times (bounded memory), then the signature falls
  back to eager permanently, with a warning each time pointing at
  ``lax.cond``/``jnp.where`` restructuring.

Entry points: `symbolic_translate(fn)` (reference `sot/translate.py`
name) / `sot_capture(fn)`.
"""
from .capture import (  # noqa: F401
    SOTError, SOTFunction, sot_capture, symbolic_translate,
)

__all__ = ["symbolic_translate", "sot_capture", "SOTFunction", "SOTError"]
