from .api import TranslatedLayer, load, not_to_static, save, to_static  # noqa: F401



_to_static_enabled = True


def enable_to_static(enable=True):
    """Globally toggle to_static conversion (reference
    jit.enable_to_static): when off, decorated functions run eagerly."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


def ignore_module(modules):
    """Reference: exclude modules from dy2static conversion. The AST
    tier already skips non-convertible callees by module allowlist
    (dy2static._jst_call); recorded here for API parity."""
    from . import dy2static

    skip = getattr(dy2static, "_IGNORED_MODULES", set())
    for m in (modules if isinstance(modules, (list, tuple)) else [modules]):
        skip.add(getattr(m, "__name__", str(m)))
    dy2static._IGNORED_MODULES = skip


def set_code_level(level=100, also_to_stdout=False):
    """Debug knob (SOT code-dump level in the reference): here controls
    whether converted AST source is printed."""
    from . import dy2static

    dy2static._DEBUG_LEVEL = level


def set_verbosity(level=0, also_to_stdout=False):
    from . import dy2static

    dy2static._VERBOSITY = level
