"""paddle_tpu.text: text-domain utilities.

Role parity: `paddle.text` (`python/paddle/text/`) — dataset helpers plus
`viterbi_decode` (the one compute op; reference kernel
`paddle/phi/kernels/cpu/viterbi_decode_kernel.cc`).

TPU-first: Viterbi is a `lax.scan` over the sequence (compiler-friendly,
batched); datasets are host-side iterators as in the reference.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decoding (parity: paddle.text.viterbi_decode).

    potentials: [B, T, N] emission scores; transition_params: [N, N];
    lengths: [B] int. Returns (scores [B], paths [B, T]).
    """
    lens = (lengths._value if isinstance(lengths, Tensor)
            else jnp.asarray(lengths)).astype(jnp.int32)

    def f(pot, trans):
        B, T, N = pot.shape
        if include_bos_eos_tag:
            # reference semantics: tag N-2 is BOS, N-1 is EOS
            start = trans[N - 2, :][None, :]
            init = pot[:, 0] + start
        else:
            init = pot[:, 0]

        def step(carry, t):
            alpha, history_dummy = carry
            # alpha: [B, N]; trans: [N, N]; emission at t: [B, N]
            scores = alpha[:, :, None] + trans[None, :, :]  # [B, N, N]
            best_prev = jnp.argmax(scores, axis=1)          # [B, N]
            best_score = jnp.max(scores, axis=1) + pot[:, t]
            # positions beyond length keep previous alpha
            active = (t < lens)[:, None]
            new_alpha = jnp.where(active, best_score, alpha)
            return (new_alpha, 0), jnp.where(
                active, best_prev, jnp.arange(N)[None, :])

        (alpha, _), history = jax.lax.scan(
            step, (init, 0), jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, N - 1][None, :]
        last_tag = jnp.argmax(alpha, axis=-1)               # [B]
        score = jnp.max(alpha, axis=-1)

        # backtrace through history [T-1, B, N]
        def back(tag, hist_t):
            # hist_t[b, j] = best predecessor of tag j at this step; emit the
            # predecessor so ys[t] lines up with path position t
            prev = jnp.take_along_axis(hist_t, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, tags_rev = jax.lax.scan(back, last_tag, history, reverse=True)
        paths = jnp.concatenate(
            [tags_rev.transpose(1, 0), last_tag[:, None]], axis=1)
        return score, paths.astype(jnp.int32)

    pt = potentials if isinstance(potentials, Tensor) else Tensor(potentials)
    tt = transition_params if isinstance(transition_params, Tensor) \
        else Tensor(transition_params)
    return apply("viterbi_decode", f, pt, tt)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# top-level re-exports (reference paddle.text exposes the dataset
# classes directly); the loaders live in .datasets (local-archive
# pattern, see that module's docstring)
from . import datasets
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
