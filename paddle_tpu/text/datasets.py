"""paddle.text.datasets parity (`python/paddle/text/datasets/`): the seven
corpus loaders, reading LOCAL copies of the official archives.

Zero-egress build: the reference downloads each corpus on first use
(`_check_exists_and_download`); this environment has no network, so every
class requires its archive path(s) and raises loudly on ``download=True``
with nothing local — the same contract as `paddle_tpu.audio.datasets`.
Parsing, example shapes, and auxiliary APIs (`get_dict`, `get_embedding`,
`get_word_dict`) mirror the reference loaders:

- Imdb       — reference `text/datasets/imdb.py:31` (aclImdb tar)
- Imikolov   — `imikolov.py` (PTB simple-examples tar, NGRAM/SEQ)
- Movielens  — `movielens.py` (ml-1m zip, user+movie features)
- Conll05st  — `conll05.py` (SRL props bracket labels -> BIO)
- UCIHousing — `uci_housing.py` (whitespace floats, normalized)
- WMT14      — `wmt14.py` (src/trg dicts inside the tar)
- WMT16      — `wmt16.py` (dict built from the training split)
"""
from __future__ import annotations

import collections
import gzip
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "Conll05st", "UCIHousing",
           "WMT14", "WMT16"]


def _need_file(data_file, download, name, what="archive"):
    if data_file:
        if not os.path.exists(data_file):
            raise FileNotFoundError(f"{name}: {what} not found: {data_file}")
        return data_file
    raise RuntimeError(
        f"{name} requires a local {what} (no network egress in this build"
        f"{'; download=True unsupported' if download else ''}): obtain the "
        f"official archive and pass data_file=")


def imdb_tokenize(data_file, pattern):
    """Token lists (bytes, lowercased, punctuation stripped) of every tar
    member matching `pattern` — shared by the Imdb Dataset class and the
    legacy `paddle_tpu.dataset.imdb` reader API."""
    docs = []
    strip = string.punctuation.encode("latin-1")
    with tarfile.open(data_file) as tf:
        member = tf.next()
        while member is not None:
            if pattern.match(member.name):
                raw = tf.extractfile(member).read()
                docs.append(
                    raw.rstrip(b"\n\r").translate(None, strip)
                    .lower().split())
            member = tf.next()
    return docs


class Imdb(Dataset):
    """IMDB sentiment (aclImdb_v1.tar.gz). Examples: (doc_ids [T] int64,
    label [1]) with label 0=pos 1=neg; vocabulary built from the whole
    corpus with frequency > cutoff (reference imdb.py:31)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, download, "Imdb")
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        return imdb_tokenize(self.data_file, pattern)

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        freq = collections.defaultdict(int)
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model corpus (simple-examples tar). NGRAM mode yields
    window_size-grams; SEQ mode yields (src, trg) shifted sequences
    (reference imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.data_file = _need_file(data_file, download, "Imikolov")
        self.word_idx = self._build_word_dict()
        self._load_anno()

    def _member(self, tf, suffix):
        for name in tf.getnames():
            if name.endswith(suffix):
                return tf.extractfile(name)
        raise RuntimeError(f"Imikolov: no member *{suffix} in archive")

    def _count(self, f, freq):
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _build_word_dict(self):
        with tarfile.open(self.data_file) as tf:
            freq = self._count(
                self._member(tf, "data/ptb.valid.txt"),
                self._count(self._member(tf, "data/ptb.train.txt"),
                            collections.defaultdict(int)))
        freq.pop(b"<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items()
                       if c > self.min_word_freq),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w.decode(): i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        name = {"train": "data/ptb.train.txt",
                "test": "data/ptb.valid.txt"}[self.mode]
        unk = self.word_idx["<unk>"]
        self.data = []
        with tarfile.open(self.data_file) as tf:
            for line in self._member(tf, name):
                words = [w.decode() for w in line.strip().split()]
                if self.data_type == "NGRAM":
                    assert self.window_size > 0, "Invalid gram length"
                    toks = ["<s>"] + words + ["<e>"]
                    if len(toks) < self.window_size:
                        continue
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(
                            tuple(ids[i - self.window_size:i]))
                else:
                    ids = [self.word_idx.get(w, unk) for w in words]
                    src = [self.word_idx["<s>"]] + ids
                    trg = ids + [self.word_idx["<e>"]]
                    if 0 < self.window_size < len(src):
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_AGE_TABLE = (1, 18, 25, 35, 45, 50, 56)


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """MovieLens ml-1m ratings (zip). Each example: user features + movie
    features + [rating*2-5] (reference movielens.py)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, download, "Movielens")
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        rs = np.random.RandomState(rand_seed)
        self._load_meta_info()
        self._load_data(rs)

    def _load_meta_info(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        self.movie_title_dict, self.categories_dict = {}, {}
        title_words, category_set = set(), set()
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin1").strip() \
                        .split("::")
                    cats = cats.split("|")
                    category_set.update(cats)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    for w in title.split():
                        title_words.add(w.lower())
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode("latin1") \
                        .strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
        # deterministic ids (the reference iterates a set — order varies
        # per process; sorting keeps examples reproducible)
        self.movie_title_dict = {w: i for i, w in
                                 enumerate(sorted(title_words))}
        self.categories_dict = {c: i for i, c in
                                enumerate(sorted(category_set))}

    def _load_data(self, rs):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    take = (rs.random_sample() < self.test_ratio) == is_test
                    if not take:
                        continue
                    uid, mid, rating, _ = line.decode("latin1").strip() \
                        .split("::")
                    rating = float(rating) * 2 - 5.0
                    self.data.append(
                        self.user_info[int(uid)].value()
                        + self.movie_info[int(mid)].value(
                            self.categories_dict, self.movie_title_dict)
                        + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_CONLL_UNK_IDX = 0


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split. Examples are the reference's 9-tuple:
    (word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
    label_idx), each [T] (reference conll05.py: bracketed props ->
    B-/I-/O tags, predicate context windows)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        self.data_file = _need_file(data_file, download, "Conll05st")
        self.word_dict_file = _need_file(word_dict_file, download,
                                         "Conll05st", "word dict file")
        self.verb_dict_file = _need_file(verb_dict_file, download,
                                         "Conll05st", "verb dict file")
        self.target_dict_file = _need_file(target_dict_file, download,
                                           "Conll05st", "target dict file")
        self.emb_file = emb_file  # optional; only handed back
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(path):
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d, idx = {}, 0
        for tag in sorted(tags):
            d["B-" + tag] = idx
            d["I-" + tag] = idx + 1
            idx += 2
        d["O"] = idx
        return d

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                sentence, columns = [], []
                for word, prop in zip(words, props):
                    word = word.strip().decode()
                    prop = prop.strip().decode().split()
                    if prop:
                        sentence.append(word)
                        columns.append(prop)
                        continue
                    self._emit_sentence(sentence, columns)
                    sentence, columns = [], []
                self._emit_sentence(sentence, columns)

    def _emit_sentence(self, sentence, columns):
        if not columns:
            return
        rows = list(zip(*columns))  # rows[i] = column i down the sentence
        verbs = [v for v in rows[0] if v != "-"]
        for vi, col in enumerate(rows[1:]):
            tags, cur, in_bracket = [], None, False
            for tok in col:
                if tok == "*":
                    tags.append("I-" + cur if in_bracket else "O")
                elif tok == "*)":
                    tags.append("I-" + cur)
                    in_bracket = False
                elif "(" in tok and ")" in tok:
                    cur = tok[1:tok.find("*")]
                    tags.append("B-" + cur)
                    in_bracket = False
                elif "(" in tok:
                    cur = tok[1:tok.find("*")]
                    tags.append("B-" + cur)
                    in_bracket = True
                else:
                    raise RuntimeError(f"unexpected SRL label: {tok}")
            self.sentences.append(list(sentence))
            self.predicates.append(verbs[vi])
            self.labels.append(tags)

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        labels = self.labels[idx]
        n = len(sentence)
        vi = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                              (0, "0", None), (1, "p1", "eos"),
                              (2, "p2", "eos")):
            j = vi + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[key] = sentence[j]
            else:
                ctx[key] = pad
        word_idx = [self.word_dict.get(w, _CONLL_UNK_IDX) for w in sentence]
        ctx_arr = {k: [self.word_dict.get(v, _CONLL_UNK_IDX)] * n
                   for k, v in ctx.items()}
        pred_idx = [self.predicate_dict.get(self.predicates[idx])] * n
        label_idx = [self.label_dict.get(t) for t in labels]
        return (np.array(word_idx), np.array(ctx_arr["n2"]),
                np.array(ctx_arr["n1"]), np.array(ctx_arr["0"]),
                np.array(ctx_arr["p1"]), np.array(ctx_arr["p2"]),
                np.array(pred_idx), np.array(mark), np.array(label_idx))

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        return self.emb_file


class UCIHousing(Dataset):
    """Boston housing: 14 whitespace-separated floats per row; features
    mean-normalized by (max-min); 80/20 train/test split (reference
    uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, download, "UCIHousing",
                                    "data file")
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.mean(axis=0)
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype(np.float32), row[-1:].astype(np.float32))

    def __len__(self):
        return len(self.data)


_WMT_START, _WMT_END, _WMT_UNK = "<s>", "<e>", "<unk>"
_WMT_UNK_IDX = 2


class WMT14(Dataset):
    """WMT14 en-fr (preprocessed tar with src.dict/trg.dict inside).
    Examples: (src_ids, trg_ids, trg_ids_next), sequences over 80 tokens
    dropped (reference wmt14.py)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, download, "WMT14")
        assert dict_size > 0, "dict_size should be a positive number"
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(f, size):
            d = {}
            for i, line in enumerate(f):
                if i >= size:
                    break
                d[line.strip().decode()] = i
            return d

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            names = tf.getnames()
            src = [n for n in names if n.endswith("src.dict")]
            trg = [n for n in names if n.endswith("trg.dict")]
            assert len(src) == 1 and len(trg) == 1, \
                "archive must contain exactly one src.dict and trg.dict"
            self.src_dict = to_dict(tf.extractfile(src[0]), self.dict_size)
            self.trg_dict = to_dict(tf.extractfile(trg[0]), self.dict_size)
            suffix = f"{self.mode}/{self.mode}"
            for name in (n for n in names if n.endswith(suffix)):
                for line in tf.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [self.src_dict.get(w, _WMT_UNK_IDX)
                               for w in ([_WMT_START] + parts[0].split()
                                         + [_WMT_END])]
                    trg_words = [self.trg_dict.get(w, _WMT_UNK_IDX)
                                 for w in parts[1].split()]
                    if len(src_ids) > 80 or len(trg_words) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids.append(
                        [self.trg_dict[_WMT_START]] + trg_words)
                    self.trg_ids_next.append(
                        trg_words + [self.trg_dict[_WMT_END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """WMT16 en-de (tar with wmt16/{train,test,val} TSVs). Vocabularies
    are built from the training split in memory (the reference caches
    them under DATA_HOME; a pure function of the archive is kept here)
    (reference wmt16.py)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode.lower() in ("train", "test", "val"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, download, "WMT16")
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict sizes should be positive numbers"
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        self.src_dict = self._build_dict(src_dict_size, lang)
        self.trg_dict = self._build_dict(trg_dict_size,
                                         "de" if lang == "en" else "en")
        self._load_data()

    def _build_dict(self, dict_size, lang):
        col = 0 if lang == "en" else 1
        freq = collections.defaultdict(int)
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        words = [_WMT_START, _WMT_END, _WMT_UNK]
        for w, _ in sorted(freq.items(), key=lambda x: (-x[1], x[0])):
            if len(words) == dict_size:
                break
            words.append(w)
        return {w: i for i, w in enumerate(words)}

    def _load_data(self):
        start_id = self.src_dict[_WMT_START]
        end_id = self.src_dict[_WMT_END]
        unk_id = self.src_dict[_WMT_UNK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [self.src_dict.get(w, unk_id)
                       for w in parts[src_col].split()]
                trg = [self.trg_dict.get(w, unk_id)
                       for w in parts[1 - src_col].split()]
                self.src_ids.append([start_id] + src + [end_id])
                self.trg_ids.append([start_id] + trg)
                self.trg_ids_next.append(trg + [end_id])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)
