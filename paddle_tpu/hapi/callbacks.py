"""Callbacks (parity: `python/paddle/hapi/callbacks.py`)."""
from __future__ import annotations

import numbers
import time



class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and self.log_freq and step % self.log_freq == 0:
            logs = logs or {}
            parts = []
            for k, v in logs.items():
                if k == "step":
                    continue
                if isinstance(v, (list, tuple)):
                    v = v[0] if v else 0.0
                if isinstance(v, numbers.Number):
                    parts.append(f"{k}: {v:.4f}")
            print(f"  step {step}: " + ", ".join(parts))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"  epoch time: {time.time() - self.t0:.1f}s")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def _metric_comparator(mode, monitor, min_delta):
    """'min'/'max'/'auto' improvement test shared by the monitor-driven
    callbacks ('auto' infers max for accuracy-like monitors)."""
    if mode == "max" or (mode == "auto" and "acc" in monitor):
        return lambda a, b: a > b + min_delta
    return lambda a, b: a < b - min_delta


def _unwrap_metric(logs, monitor):
    cur = (logs or {}).get(monitor)
    if isinstance(cur, (list, tuple)):
        cur = cur[0] if cur else None
    return cur


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        self.better = _metric_comparator(mode, monitor, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _unwrap_metric(logs, self.monitor)
        if cur is None:
            return
        if self.best is None or self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Metric logger writing jsonl (the reference logs to VisualDL; here a
    dependency-free structured log with the same lifecycle)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/metrics.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        if self._f is None:
            return
        import json

        clean = {k: (float(v[0]) if isinstance(v, (list, tuple)) and v else
                     float(v) if isinstance(v, numbers.Number) else None)
                 for k, v in (logs or {}).items()}
        self._f.write(json.dumps({"step": step, **clean}) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR by `factor` when `monitor` stops improving
    (reference hapi/callbacks.py ReduceLROnPlateau). Works on plain-float
    learning rates (set_lr); scheduler-driven optimizers keep their
    schedule — the callback warns once and does nothing."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError(
                "ReduceLROnPlateau does not support a factor >= 1.0")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.wait = 0
        self.cooldown_counter = 0
        self.best = None
        self._warned = False
        self.better = _metric_comparator(mode, monitor, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _unwrap_metric(logs, self.monitor)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.best is None or self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            return
        self.wait += 1
        if self.wait < self.patience:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        try:
            old = opt.get_lr()
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
        except RuntimeError:
            if not self._warned:
                import warnings

                warnings.warn(
                    "ReduceLROnPlateau: optimizer uses an LRScheduler; "
                    "the callback cannot override it and will do nothing")
                self._warned = True
            return
        self.cooldown_counter = self.cooldown
        self.wait = 0


class WandbCallback(Callback):
    """Weights & Biases logger (reference hapi/callbacks.py WandbCallback).
    This environment has no network egress and no wandb package; the
    callback raises at construction with that reason (documented gate,
    not a silent no-op)."""

    def __init__(self, *a, **kw):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the `wandb` package, which is not "
                "available in this environment (no network egress); use "
                "the VisualDL jsonl logger callback instead") from e
        raise NotImplementedError(
            "wandb import unexpectedly succeeded; hook up run logging "
            "before using WandbCallback")
