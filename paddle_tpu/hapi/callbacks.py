"""Callbacks (parity: `python/paddle/hapi/callbacks.py`)."""
from __future__ import annotations

import numbers
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and self.log_freq and step % self.log_freq == 0:
            logs = logs or {}
            parts = []
            for k, v in logs.items():
                if k == "step":
                    continue
                if isinstance(v, (list, tuple)):
                    v = v[0] if v else 0.0
                if isinstance(v, numbers.Number):
                    parts.append(f"{k}: {v:.4f}")
            print(f"  step {step}: " + ", ".join(parts))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"  epoch time: {time.time() - self.t0:.1f}s")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
        else:
            self.better = lambda a, b: a < b - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Metric logger writing jsonl (the reference logs to VisualDL; here a
    dependency-free structured log with the same lifecycle)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/metrics.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        if self._f is None:
            return
        import json

        clean = {k: (float(v[0]) if isinstance(v, (list, tuple)) and v else
                     float(v) if isinstance(v, numbers.Number) else None)
                 for k, v in (logs or {}).items()}
        self._f.write(json.dumps({"step": step, **clean}) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
