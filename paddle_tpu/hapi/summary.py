"""paddle.summary / paddle.flops parity (`python/paddle/hapi/model_summary.py`,
`python/paddle/hapi/dynamic_flops.py`): layer table + param/FLOP counts via
forward hooks."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _make_input(input_size, dtypes):
    import paddle_tpu as P

    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        return [_make_input(s, dtypes) for s in input_size]
    shape = [1 if (s is None or s == -1) else int(s) for s in input_size]
    dt = dtypes or "float32"
    if "int" in str(dt):
        return P.to_tensor(np.zeros(shape, np.int64))
    return P.to_tensor(np.zeros(shape, np.float32))


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; return dict with total/trainable params."""
    records = []
    hooks = []

    def register(layer):
        def hook(l, inputs, output):
            out_shape = None
            out = output
            if isinstance(out, (list, tuple)) and out:
                out = out[0]
            if isinstance(out, Tensor):
                out_shape = list(out.shape)
            n_params = sum(int(np.prod(p.shape))
                           for p in l.parameters(include_sublayers=False))
            records.append((type(l).__name__, out_shape, n_params))

        if not layer.sublayers():
            hooks.append(layer.register_forward_post_hook(hook))

    for l in net.sublayers(include_self=True):
        register(l)

    try:
        x = input if input is not None else _make_input(input_size, dtypes)
        if isinstance(x, (list, tuple)):
            net(*x)
        else:
            net(x)
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient and getattr(p, "trainable", True))
    line = "-" * 64
    print(line)
    print(f"{'Layer (type)':<24}{'Output Shape':<24}{'Param #':<12}")
    print(line)
    for name, shape, n in records:
        print(f"{name:<24}{str(shape):<24}{n:<12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}


_FLOP_RULES = {}


def _conv_flops(l, inp, out):
    k = int(np.prod(l.kernel_size))
    cin = l.in_channels // getattr(l, "groups", 1)
    out_numel = int(np.prod(out.shape))
    return out_numel * cin * k


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total multiply-accumulate count of one forward (paddle.flops)."""
    from ..nn.layers_common import Linear
    from ..nn.layers_conv_pool import _ConvNd

    total = [0]
    hooks = []

    def register(layer):
        def hook(l, inputs, output):
            out = output[0] if isinstance(output, (list, tuple)) else output
            if custom_ops and type(l) in custom_ops:
                total[0] += int(custom_ops[type(l)](l, inputs, out))
            elif isinstance(l, _ConvNd):
                total[0] += _conv_flops(l, inputs, out)
            elif isinstance(l, Linear):
                total[0] += int(np.prod(l.weight.shape)) * (
                    int(np.prod(out.shape)) // out.shape[-1])

        hooks.append(layer.register_forward_post_hook(hook))

    for l in net.sublayers(include_self=True):
        register(l)
    try:
        x = _make_input(input_size, None)
        net(x)
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs (MACs): {total[0]:,}")
    return total[0]
