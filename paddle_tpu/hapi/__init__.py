from .callbacks import Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger  # noqa: F401
from .model import Model  # noqa: F401
