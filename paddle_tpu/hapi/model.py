"""High-level Model API (parity: `python/paddle/hapi/model.py:1054` —
Model.prepare/fit/evaluate/predict/save/load with callbacks + metrics)."""
from __future__ import annotations

import numpy as np

from ..framework.io_utils import load as fload, save as fsave
from .callbacks import CallbackList, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        # AMP (reference Model.prepare amp_configs): "O1"/"O2" or a dict
        # {"level": ..., "dtype": ...}; O2 decorates params to the compute
        # dtype, O1 autocasts per-op inside train/eval_batch
        self._amp_level = "O0"
        self._amp_dtype = "bfloat16"
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            else:
                self._amp_level = amp_configs.get("level", "O1")
                self._amp_dtype = amp_configs.get("dtype", "bfloat16")
            if self._amp_level == "O2":
                import paddle_tpu as P

                self.network, self._optimizer = P.amp.decorate(
                    self.network, self._optimizer, level="O2",
                    dtype=self._amp_dtype)
        # distributed fit: with an initialized dp>1 hybrid topology the
        # network is wrapped so backward syncs grads across dp ranks
        # (reference: hapi Model under paddle.DataParallel)
        try:
            from ..distributed import topology as _topo

            topo = _topo._topology  # only an ALREADY-initialized topology
            if topo is not None and getattr(topo, 'dp_degree', 1) > 1:
                from ..distributed.parallel import DataParallel

                if not isinstance(self.network, DataParallel):
                    self.network = DataParallel(self.network)
        except Exception as e:
            # auto-wrap is best-effort (the model still runs
            # un-wrapped) — but a dp>1 topology that fails to wrap is
            # silent data-parallel loss; leave the evidence
            from ..observability import flight as _flight

            _flight.record("hapi.data_parallel_wrap_failed",
                           error=repr(e))
        return self

    # --- single steps --------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        import paddle_tpu as P

        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if getattr(self, "_amp_level", "O0") in ("O1", "O2"):
            with P.amp.auto_cast(level=self._amp_level,
                                 dtype=self._amp_dtype):
                outs = self.network(*inputs)
                losses = self._compute_loss(outs, labels)
        else:
            outs = self.network(*inputs)
            losses = self._compute_loss(outs, labels)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return [float(l) for l in losses], metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        import paddle_tpu as P

        with P.no_grad():
            outs = self.network(*_to_list(inputs))
            losses = self._compute_loss(outs, _to_list(labels))
        metrics = self._update_metrics(outs, _to_list(labels))
        return [float(l) for l in losses], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        import paddle_tpu as P

        with P.no_grad():
            outs = self.network(*_to_list(inputs))
        return [o.numpy() for o in _to_list(outs)]

    def _compute_loss(self, outs, labels):
        outs_l = _to_list(outs)
        if self._loss is None:
            return outs_l
        return _to_list(self._loss(*(outs_l + labels)))

    def _update_metrics(self, outs, labels):
        res = {}
        outs_l = _to_list(outs)
        for m in self._metrics:
            inp = m.compute(*(outs_l + labels))
            r = m.update(inp) if not isinstance(inp, (list, tuple)) else \
                m.update(*inp)
            res[m.name()] = r
        return res

    # --- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        cbks = CallbackList(_to_list(callbacks) or
                            [ProgBarLogger(log_freq, verbose=verbose)])
        cbks.set_model(self)
        cbks.set_params({
            "epochs": epochs, "steps": self._len_or_none(train_loader),
            "verbose": verbose, "metrics": ["loss"] + [
                m.name() for m in self._metrics],
        })
        cbks.on_begin("train")
        it = 0
        done = False
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            pending = 0
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                # gradient accumulation (reference accumulate_grad_batches):
                # grads add up across micro-batches; step every k-th
                pending += 1
                update = pending % max(1, accumulate_grad_batches) == 0
                losses, metrics = self.train_batch(inputs, labels,
                                                   update=update)
                logs = {"loss": losses, **metrics, "step": step}
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    done = True
                    break
            if pending % max(1, accumulate_grad_batches) != 0:
                # flush the tail micro-batches
                self._optimizer.step()
                self._optimizer.clear_grad()
            cbks.on_epoch_end(epoch, logs if "logs" in dir() else {})
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, callbacks=callbacks,
                              verbose=verbose)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            if self.stop_training or done:
                break
        cbks.on_end("train")

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset

        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        cbks = CallbackList(_to_list(callbacks) or [])
        cbks.set_model(self)
        cbks.on_begin("eval")
        for m in self._metrics:
            m.reset()
        total_loss = 0.0
        n = 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            losses, _ = self.eval_batch(inputs, labels)
            total_loss += sum(losses)
            n += 1
            cbks.on_eval_batch_end(step, {"loss": losses})
            if num_samples is not None and n * batch_size >= num_samples:
                break
        res = {"loss": total_loss / max(1, n)}
        for m in self._metrics:
            res[m.name()] = m.accumulate()
        cbks.on_end("eval", res)
        return res

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset

        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # --- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(fload(path + ".pdparams"))
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *a, **kw):
        return self.network.parameters(*a, **kw)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(int(np.prod(p.shape))
                       for p in self.network.parameters())
        trainable = sum(int(np.prod(p.shape))
                        for p in self.network.parameters()
                        if getattr(p, "trainable", True))
        text = (f"Total params: {n_params:,}\n"
                f"Trainable params: {trainable:,}\n"
                f"Non-trainable params: {n_params - trainable:,}")
        print(text)
        return {"total_params": n_params, "trainable_params": trainable}

    @staticmethod
    def _len_or_none(loader):
        try:
            return len(loader)
        except Exception:
            return None

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) == 2:
                return [batch[0]], [batch[1]]
            return list(batch[:-1]), [batch[-1]]
        return [batch], []
