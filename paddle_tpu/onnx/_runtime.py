"""Minimal numpy evaluator for the ONNX subset this framework emits.

Purpose: (a) CI verifies exported models numerically without an
onnxruntime wheel (this image has none); (b) users get
`paddle_tpu.onnx.run_reference(path, inputs)` to sanity-check an export
before shipping it to a real ONNX runtime. This is NOT a general ONNX
runtime — it implements exactly the ops `_jaxpr_export.py` can produce
and raises loudly on anything else.
"""
from __future__ import annotations

import math

import numpy as np

from . import _schema

_NP_DTYPE = {
    _schema.FLOAT: np.float32,
    _schema.DOUBLE: np.float64,
    _schema.FLOAT16: np.float16,
    _schema.INT32: np.int32,
    _schema.INT64: np.int64,
    _schema.INT8: np.int8,
    _schema.UINT8: np.uint8,
    _schema.BOOL: np.bool_,
}


def _tensor_to_np(t):
    dt = _NP_DTYPE[t.data_type]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dt).reshape(tuple(t.dims)).copy()
    if t.data_type == _schema.FLOAT:
        return np.asarray(t.float_data, dt).reshape(tuple(t.dims))
    if t.data_type in (_schema.INT64,):
        return np.asarray(t.int64_data, dt).reshape(tuple(t.dims))
    return np.asarray(t.int32_data, dt).reshape(tuple(t.dims))


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == 1:
            out[a.name] = a.f
        elif a.type == 2:
            out[a.name] = a.i
        elif a.type == 3:
            out[a.name] = a.s.decode()
        elif a.type == 6:
            out[a.name] = list(a.floats)
        elif a.type == 7:
            out[a.name] = list(a.ints)
        else:
            raise NotImplementedError(f"attr type {a.type}")
    return out


def _conv2d(x, w, b=None, *, strides, pads, group=1, dilations=None):
    n, cin, h, wdt = x.shape
    cout, cink, kh, kw = w.shape
    dh, dw = (dilations or [1, 1])
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    eh = (kh - 1) * dh + 1
    ew = (kw - 1) * dw + 1
    oh = (xp.shape[2] - eh) // strides[0] + 1
    ow = (xp.shape[3] - ew) // strides[1] + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    cpg_in = cin // group
    cpg_out = cout // group
    for g in range(group):
        xs = xp[:, g * cpg_in:(g + 1) * cpg_in]
        ws = w[g * cpg_out:(g + 1) * cpg_out]
        for i in range(oh):
            for j in range(ow):
                patch = xs[:, :, i * strides[0]:i * strides[0] + eh:dh,
                           j * strides[1]:j * strides[1] + ew:dw]
                out[:, g * cpg_out:(g + 1) * cpg_out, i, j] = np.einsum(
                    "nchw,ochw->no", patch, ws)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def _maxpool(x, *, kernel_shape, strides, pads):
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=-np.inf)
    kh, kw = kernel_shape
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    out = np.full((x.shape[0], x.shape[1], oh, ow), -np.inf, x.dtype)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = xp[:, :, i * strides[0]:i * strides[0] + kh,
                                 j * strides[1]:j * strides[1] + kw
                                 ].max((2, 3))
    return out


_ERF = np.vectorize(math.erf)


def run_model(model, inputs: dict) -> dict:
    """Evaluate a ModelProto emitted by `_jaxpr_export` on numpy inputs."""
    g = model.graph
    env = dict(inputs)
    for init in g.initializer:
        env[init.name] = _tensor_to_np(init)
    for vi in g.input:
        if vi.name not in env:
            raise ValueError(f"missing input {vi.name}")

    def A(i):
        return env[node.input[i]]

    for node in g.node:
        a = _attrs(node)
        op = node.op_type
        if op == "Identity":
            r = A(0)
        elif op in ("Add", "Sub", "Mul", "Div", "Pow", "Mod"):
            f = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
                 "Div": np.divide, "Pow": np.power, "Mod": np.mod}[op]
            r = f(A(0), A(1))
        elif op in ("Max", "Min"):
            r = (np.maximum if op == "Max" else np.minimum)(A(0), A(1))
        elif op in ("Equal", "Less", "LessOrEqual", "Greater",
                    "GreaterOrEqual"):
            f = {"Equal": np.equal, "Less": np.less,
                 "LessOrEqual": np.less_equal, "Greater": np.greater,
                 "GreaterOrEqual": np.greater_equal}[op]
            r = f(A(0), A(1))
        elif op in ("And", "Or", "Xor"):
            f = {"And": np.logical_and, "Or": np.logical_or,
                 "Xor": np.logical_xor}[op]
            r = f(A(0), A(1))
        elif op == "Not":
            r = np.logical_not(A(0))
        elif op in ("Exp", "Log", "Tanh", "Abs", "Neg", "Sign", "Floor",
                    "Ceil", "Round", "Sqrt", "Sin", "Cos", "Tan", "Asin",
                    "Acos", "Atan", "Sinh", "Cosh", "Reciprocal"):
            f = {"Exp": np.exp, "Log": np.log, "Tanh": np.tanh,
                 "Abs": np.abs, "Neg": np.negative, "Sign": np.sign,
                 "Floor": np.floor, "Ceil": np.ceil, "Round": np.round,
                 "Sqrt": np.sqrt, "Sin": np.sin, "Cos": np.cos,
                 "Tan": np.tan, "Asin": np.arcsin, "Acos": np.arccos,
                 "Atan": np.arctan, "Sinh": np.sinh, "Cosh": np.cosh,
                 "Reciprocal": np.reciprocal}[op]
            r = f(A(0))
        elif op == "Sigmoid":
            r = 1.0 / (1.0 + np.exp(-A(0)))
        elif op == "Erf":
            r = _ERF(A(0)).astype(A(0).dtype)
        elif op == "Einsum":
            r = np.einsum(a["equation"], *[A(i)
                                           for i in range(len(node.input))])
        elif op == "Reshape":
            r = A(0).reshape(tuple(int(x) for x in A(1)))
        elif op == "Transpose":
            r = np.transpose(A(0), a["perm"])
        elif op == "Expand":
            r = np.broadcast_to(A(0), tuple(int(x) for x in A(1))).copy()
        elif op == "ReduceSum":
            axes = tuple(int(x) for x in A(1))
            r = A(0).sum(axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
            f = {"ReduceMax": np.max, "ReduceMin": np.min,
                 "ReduceProd": np.prod}[op]
            r = f(A(0), axis=tuple(a["axes"]),
                  keepdims=bool(a.get("keepdims", 1)))
        elif op == "Conv":
            bias = A(2) if len(node.input) > 2 else None
            r = _conv2d(A(0), A(1), bias, strides=a["strides"],
                        pads=a["pads"], group=a.get("group", 1),
                        dilations=a.get("dilations"))
        elif op == "MaxPool":
            r = _maxpool(A(0), kernel_shape=a["kernel_shape"],
                         strides=a["strides"], pads=a["pads"])
        elif op == "Where":
            r = np.where(A(0), A(1), A(2))
        elif op == "Cast":
            r = A(0).astype(_NP_DTYPE[a["to"]])
        elif op == "Concat":
            r = np.concatenate([A(i) for i in range(len(node.input))],
                               axis=a["axis"])
        elif op == "Slice":
            starts = [int(x) for x in A(1)]
            ends = [int(x) for x in A(2)]
            axes = [int(x) for x in A(3)]
            steps = ([int(x) for x in A(4)]
                     if len(node.input) > 4 else [1] * len(axes))
            sl = [slice(None)] * A(0).ndim
            for ax, st, en, sp in zip(axes, starts, ends, steps):
                sl[ax] = slice(st, en, sp)
            r = A(0)[tuple(sl)]
        elif op == "Squeeze":
            r = np.squeeze(A(0), axis=tuple(int(x) for x in A(1)))
        elif op == "Pad":
            pads = [int(x) for x in A(1)]
            nd = A(0).ndim
            val = float(A(2)) if len(node.input) > 2 else 0.0
            width = [(pads[i], pads[nd + i]) for i in range(nd)]
            r = np.pad(A(0), width, constant_values=val)
        else:
            raise NotImplementedError(f"reference runtime: op {op}")
        env[node.output[0]] = r
    return {vo.name: env[vo.name] for vo in g.output}


def load_model(path):
    C = _schema.classes()
    m = C["ModelProto"]()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m


def run_reference(path, inputs: dict) -> dict:
    """Load a saved .onnx file and evaluate it with the numpy evaluator."""
    return run_model(load_model(path), inputs)
