"""jaxpr → ONNX GraphProto converter (real ONNX emission).

Role parity: `python/paddle/onnx/export.py` (paddle2onnx's Program→ONNX
translation). TPU-first: the framework's single graph IR is the traced
jaxpr, so ONNX export is a jaxpr walk — each supported primitive maps to
one or a few ONNX-17 nodes; unsupported primitives raise loudly with the
primitive name (no silent partial export).

Covered primitive families (enough for MLP/conv/transformer inference
graphs): elementwise math, matmul/einsum (dot_general), reductions,
shape ops (reshape/transpose/broadcast/concat/slice/squeeze/pad),
conv_general_dilated (NCHW/OIHW), select_n, casts, constants, and the
call wrappers (pjit / custom_jvp / custom_vjp / remat) which are inlined.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import _schema

_ONNX_DTYPE = {
    "float32": _schema.FLOAT,
    "float64": _schema.DOUBLE,
    "float16": _schema.FLOAT16,
    "bfloat16": _schema.BFLOAT16,
    "int32": _schema.INT32,
    "int64": _schema.INT64,
    "int8": _schema.INT8,
    "uint8": _schema.UINT8,
    "bool": _schema.BOOL,
}


def _np_for_onnx(arr):
    """numpy array in an ONNX-serializable dtype (bf16 → f32)."""
    a = np.asarray(arr)
    if a.dtype.name == "bfloat16":
        a = a.astype(np.float32)
    return a


class _Builder:
    def __init__(self):
        C = _schema.classes()
        self.C = C
        self.graph = C["GraphProto"]()
        self.names = {}      # jax Var -> onnx value name
        self.counter = 0
        self.const_cache = {}

    def fresh(self, hint="v"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, atom):
        if isinstance(atom, jcore.Literal):
            return self.constant(np.asarray(atom.val))
        if atom not in self.names:
            self.names[atom] = self.fresh("t")
        return self.names[atom]

    def node(self, op_type, inputs, n_out=1, name_hint=None, **attrs):
        n = self.graph.node.add()
        n.op_type = op_type
        n.name = self.fresh(name_hint or op_type.lower())
        n.input.extend(inputs)
        outs = [self.fresh(f"{op_type.lower()}_out") for _ in range(n_out)]
        n.output.extend(outs)
        for k, v in attrs.items():
            a = n.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.f = v
                a.type = 1  # FLOAT
            elif isinstance(v, bool) or isinstance(v, (int, np.integer)):
                a.i = int(v)
                a.type = 2  # INT
            elif isinstance(v, (bytes, str)):
                a.s = v.encode() if isinstance(v, str) else v
                a.type = 3  # STRING
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, np.integer)) for x in v):
                a.ints.extend(int(x) for x in v)
                a.type = 7  # INTS
            elif isinstance(v, (list, tuple)):
                a.floats.extend(float(x) for x in v)
                a.type = 6  # FLOATS
            else:
                raise TypeError(f"attr {k}={v!r}")
        return outs if n_out > 1 else outs[0]

    def tensor_proto(self, arr, name):
        arr = _np_for_onnx(arr)
        t = self.C["TensorProto"]()
        t.name = name
        t.dims.extend(arr.shape)
        t.data_type = _ONNX_DTYPE[arr.dtype.name]
        t.raw_data = np.ascontiguousarray(arr).tobytes()
        return t

    def constant(self, arr, name=None):
        arr = _np_for_onnx(np.asarray(arr))
        key = (arr.dtype.name, arr.shape, arr.tobytes()) \
            if arr.size <= 1024 else None
        if name is None and key is not None and key in self.const_cache:
            return self.const_cache[key]
        nm = name or self.fresh("const")
        self.graph.initializer.append(self.tensor_proto(arr, nm))
        if name is None and key is not None:
            self.const_cache[key] = nm
        return nm

    def value_info(self, coll, name, aval):
        vi = coll.add()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _ONNX_DTYPE.get(
            np.dtype(aval.dtype).name
            if aval.dtype != jnp.bfloat16 else "bfloat16",
            _schema.FLOAT)
        if np.dtype(aval.dtype).name == "bfloat16":
            tt.elem_type = _schema.FLOAT  # bf16 weights exported as f32
        for d in aval.shape:
            tt.shape.dim.add().dim_value = int(d)


# ------------------------- primitive handlers --------------------------

def _ew(op_type):
    def h(b, eqn, ins):
        return [b.node(op_type, ins)]
    return h


def _binop_np(op_type):
    # jax binary prims are already broadcast-explicit (broadcast_in_dim
    # precedes them), and ONNX broadcasting is numpy-style — safe.
    return _ew(op_type)


def _dot_general(b, eqn, ins):
    dn = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dn
    lhs, rhs = eqn.invars
    lr, rr = len(lhs.aval.shape), len(rhs.aval.shape)
    # build an einsum equation (ONNX Einsum, opset>=12)
    import string

    letters = iter(string.ascii_lowercase)
    l_ax = [None] * lr
    r_ax = [None] * rr
    for i, (la, ra) in enumerate(zip(lb, rb)):
        c = next(letters)
        l_ax[la] = c
        r_ax[ra] = c
    for la, ra in zip(lc, rc):
        c = next(letters)
        l_ax[la] = c
        r_ax[ra] = c
    for i in range(lr):
        if l_ax[i] is None:
            l_ax[i] = next(letters)
    for i in range(rr):
        if r_ax[i] is None:
            r_ax[i] = next(letters)
    out = ([l_ax[i] for i in lb]
           + [l_ax[i] for i in range(lr) if i not in lb and i not in lc]
           + [r_ax[i] for i in range(rr) if i not in rb and i not in rc])
    eq = f"{''.join(l_ax)},{''.join(r_ax)}->{''.join(out)}"
    return [b.node("Einsum", ins, equation=eq)]


def _reshape(b, eqn, ins):
    shape = b.constant(np.asarray(eqn.params["new_sizes"], np.int64))
    return [b.node("Reshape", [ins[0], shape])]


def _transpose(b, eqn, ins):
    return [b.node("Transpose", ins, perm=list(eqn.params["permutation"]))]


def _broadcast_in_dim(b, eqn, ins):
    shape = eqn.params["shape"]
    bdims = eqn.params["broadcast_dimensions"]
    in_shape = eqn.invars[0].aval.shape
    # step 1: reshape input so rank matches (1s everywhere except bdims)
    mid = [1] * len(shape)
    for i, d in enumerate(bdims):
        mid[d] = in_shape[i]
    cur = ins[0]
    if list(mid) != list(in_shape):
        cur = b.node("Reshape",
                     [cur, b.constant(np.asarray(mid, np.int64))])
    if list(mid) != list(shape):
        cur = b.node("Expand",
                     [cur, b.constant(np.asarray(shape, np.int64))])
    return [cur]


def _reduce(op_type, axes_as_input):
    def h(b, eqn, ins):
        axes = [int(a) for a in eqn.params["axes"]]
        if axes_as_input:  # ReduceSum (opset 13+)
            ax = b.constant(np.asarray(axes, np.int64))
            return [b.node(op_type, [ins[0], ax], keepdims=0)]
        return [b.node(op_type, ins, axes=axes, keepdims=0)]
    return h


def _conv(b, eqn, ins):
    dn = eqn.params["dimension_numbers"]
    if dn.lhs_spec != tuple(range(len(dn.lhs_spec))):
        raise NotImplementedError(
            f"onnx export: conv layout {dn} (only NCHW/OIHW supported)")
    strides = list(eqn.params["window_strides"])
    pads = eqn.params["padding"]
    lo = [p[0] for p in pads]
    hi = [p[1] for p in pads]
    rhs_dil = list(eqn.params.get("rhs_dilation") or [])
    groups = int(eqn.params.get("feature_group_count", 1))
    kw = dict(strides=strides, pads=lo + hi, group=groups)
    if rhs_dil:
        kw["dilations"] = rhs_dil
    return [b.node("Conv", ins, **kw)]


def _select_n(b, eqn, ins):
    if len(ins) != 3:
        raise NotImplementedError("onnx export: select_n with >2 cases")
    # select_n(pred, on_false, on_true); Where(cond, X, Y): X where cond
    return [b.node("Where", [ins[0], ins[2], ins[1]])]


def _convert(b, eqn, ins):
    to = _ONNX_DTYPE[np.dtype(eqn.params["new_dtype"]).name
                     if eqn.params["new_dtype"] != jnp.bfloat16
                     else "bfloat16"]
    if to == _schema.BFLOAT16:
        to = _schema.FLOAT  # keep export f32-typed
    return [b.node("Cast", ins, to=to)]


def _integer_pow(b, eqn, ins):
    y = b.constant(np.asarray(eqn.params["y"], np.float32))
    return [b.node("Pow", [ins[0], y])]


def _rsqrt(b, eqn, ins):
    return [b.node("Reciprocal", [b.node("Sqrt", ins)])]


def _concatenate(b, eqn, ins):
    return [b.node("Concat", ins, axis=int(eqn.params["dimension"]))]


def _slice(b, eqn, ins):
    starts = b.constant(np.asarray(eqn.params["start_indices"], np.int64))
    ends = b.constant(np.asarray(eqn.params["limit_indices"], np.int64))
    axes = b.constant(np.arange(len(eqn.params["start_indices"]),
                                dtype=np.int64))
    inputs = [ins[0], starts, ends, axes]
    if eqn.params.get("strides") is not None:
        inputs.append(b.constant(
            np.asarray(eqn.params["strides"], np.int64)))
    return [b.node("Slice", inputs)]


def _squeeze(b, eqn, ins):
    axes = b.constant(np.asarray(eqn.params["dimensions"], np.int64))
    return [b.node("Squeeze", [ins[0], axes])]


def _pad(b, eqn, ins):
    cfg = eqn.params["padding_config"]
    if any(int(p[2]) != 0 for p in cfg):
        raise NotImplementedError("onnx export: interior padding")
    lo = [int(p[0]) for p in cfg]
    hi = [int(p[1]) for p in cfg]
    pads = b.constant(np.asarray(lo + hi, np.int64))
    return [b.node("Pad", [ins[0], pads, ins[1]])]


def _reduce_window_max(b, eqn, ins):
    dims = eqn.params["window_dimensions"]
    strides = eqn.params["window_strides"]
    pads = eqn.params["padding"]
    if len(dims) != 4 or dims[0] != 1 or dims[1] != 1:
        raise NotImplementedError("onnx export: non-NCHW pooling window")
    lo = [int(p[0]) for p in pads[2:]]
    hi = [int(p[1]) for p in pads[2:]]
    return [b.node("MaxPool", ins, kernel_shape=list(dims[2:]),
                   strides=list(strides[2:]), pads=lo + hi)]


def _noop(b, eqn, ins):
    return [ins[0]]


def _iota(b, eqn, ins):
    shape = eqn.params["shape"]
    dim = eqn.params["dimension"]
    n = shape[dim]
    base = np.arange(n)
    view = [1] * len(shape)
    view[dim] = n
    arr = np.broadcast_to(base.reshape(view), shape)
    return [b.constant(arr.astype(np.dtype(eqn.params["dtype"])
                                  if eqn.params["dtype"] != jnp.bfloat16
                                  else np.float32))]


_HANDLERS = {
    "add": _binop_np("Add"), "sub": _binop_np("Sub"),
    "mul": _binop_np("Mul"), "div": _binop_np("Div"),
    "max": _binop_np("Max"), "min": _binop_np("Min"),
    "pow": _binop_np("Pow"), "rem": _binop_np("Mod"),
    "eq": _binop_np("Equal"), "ne": None,  # via Equal+Not below
    "lt": _binop_np("Less"), "le": _binop_np("LessOrEqual"),
    "gt": _binop_np("Greater"), "ge": _binop_np("GreaterOrEqual"),
    "and": _binop_np("And"), "or": _binop_np("Or"),
    "xor": _binop_np("Xor"),
    "exp": _ew("Exp"), "log": _ew("Log"), "tanh": _ew("Tanh"),
    "logistic": _ew("Sigmoid"), "erf": _ew("Erf"), "abs": _ew("Abs"),
    "neg": _ew("Neg"), "sign": _ew("Sign"), "floor": _ew("Floor"),
    "ceil": _ew("Ceil"), "round": _ew("Round"), "sqrt": _ew("Sqrt"),
    "sin": _ew("Sin"), "cos": _ew("Cos"), "tan": _ew("Tan"),
    "asin": _ew("Asin"), "acos": _ew("Acos"), "atan": _ew("Atan"),
    "sinh": _ew("Sinh"), "cosh": _ew("Cosh"), "log1p": None,
    "expm1": None, "not": _ew("Not"),
    "is_finite": None,
    "rsqrt": _rsqrt,
    "integer_pow": _integer_pow,
    "dot_general": _dot_general,
    "reshape": _reshape,
    "transpose": _transpose,
    "broadcast_in_dim": _broadcast_in_dim,
    "reduce_sum": _reduce("ReduceSum", True),
    "reduce_max": _reduce("ReduceMax", False),
    "reduce_min": _reduce("ReduceMin", False),
    "reduce_prod": _reduce("ReduceProd", False),
    "conv_general_dilated": _conv,
    "select_n": _select_n,
    "convert_element_type": _convert,
    "concatenate": _concatenate,
    "slice": _slice,
    "squeeze": _squeeze,
    "pad": _pad,
    "reduce_window_max": _reduce_window_max,
    "stop_gradient": _noop,
    "copy": _noop,
    "iota": _iota,
}

_INLINE_CALLS = {"pjit", "custom_jvp_call", "custom_vjp_call",
                 "custom_vjp_call_jaxpr", "remat", "checkpoint",
                 "custom_jvp_call_jaxpr", "closed_call", "core_call",
                 "xla_call"}


def _sub_jaxpr(eqn):
    for k in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if k in eqn.params:
            j = eqn.params[k]
            return j
    return None


def _emit_eqn(b, eqn):
    prim = eqn.primitive.name
    if prim in _INLINE_CALLS or _sub_jaxpr(eqn) is not None:
        sub = _sub_jaxpr(eqn)
        if sub is None:
            raise NotImplementedError(f"onnx export: call {prim} "
                                      "without inlinable jaxpr")
        jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = getattr(sub, "consts", ())
        inner_in = [b.name_of(v) for v in eqn.invars]
        for cv, c in zip(jaxpr.constvars, consts):
            b.names[cv] = b.constant(np.asarray(c))
        for iv, nm in zip(jaxpr.invars, inner_in):
            b.names[iv] = nm
        for ieqn in jaxpr.eqns:
            _emit_eqn(b, ieqn)
        for ov, outer in zip(jaxpr.outvars, eqn.outvars):
            b.names[outer] = b.name_of(ov)
        return
    h = _HANDLERS.get(prim)
    if h is None:
        # composability fallbacks
        if prim == "log1p":
            one = b.constant(np.float32(1.0))
            x = b.name_of(eqn.invars[0])
            b.names[eqn.outvars[0]] = b.node("Log", [b.node("Add",
                                                            [x, one])])
            return
        if prim == "expm1":
            one = b.constant(np.float32(1.0))
            x = b.name_of(eqn.invars[0])
            b.names[eqn.outvars[0]] = b.node("Sub", [b.node("Exp", [x]),
                                                     one])
            return
        if prim == "erfc":
            one = b.constant(np.float32(1.0))
            x = b.name_of(eqn.invars[0])
            b.names[eqn.outvars[0]] = b.node("Sub", [one,
                                                     b.node("Erf", [x])])
            return
        if prim == "square":
            x = b.name_of(eqn.invars[0])
            b.names[eqn.outvars[0]] = b.node("Mul", [x, x])
            return
        if prim == "cbrt":
            third = b.constant(np.float32(1.0 / 3.0))
            x = b.name_of(eqn.invars[0])
            b.names[eqn.outvars[0]] = b.node("Pow", [x, third])
            return
        if prim == "ne":
            x = [b.name_of(v) for v in eqn.invars]
            b.names[eqn.outvars[0]] = b.node("Not",
                                             [b.node("Equal", x)])
            return
        raise NotImplementedError(
            f"onnx export: unsupported primitive '{prim}' "
            f"(params={list(eqn.params)}) — supported: "
            f"{sorted(k for k, v in _HANDLERS.items() if v)}")
    ins = [b.name_of(v) for v in eqn.invars]
    outs = h(b, eqn, ins)
    for ov, nm in zip(eqn.outvars, outs):
        b.names[ov] = nm


def export_jaxpr(closed_jaxpr, arg_names=None, output_names=None,
                 graph_name="paddle_tpu_graph", producer="paddle_tpu"):
    """Convert a ClosedJaxpr to an ONNX ModelProto (bytes on `.
    SerializeToString()`)."""
    C = _schema.classes()
    b = _Builder()
    jaxpr = closed_jaxpr.jaxpr
    # constants become initializers (weights)
    for cv, c in zip(jaxpr.constvars, closed_jaxpr.consts):
        b.names[cv] = b.constant(np.asarray(c), name=b.fresh("w"))
    # graph inputs
    arg_names = arg_names or [f"input_{i}"
                              for i in range(len(jaxpr.invars))]
    for iv, nm in zip(jaxpr.invars, arg_names):
        b.names[iv] = nm
        b.value_info(b.graph.input, nm, iv.aval)
    for eqn in jaxpr.eqns:
        _emit_eqn(b, eqn)
    output_names = output_names or [f"output_{i}"
                                    for i in range(len(jaxpr.outvars))]
    for ov, nm in zip(jaxpr.outvars, output_names):
        src = b.name_of(ov)
        b.node_rename = None
        # Identity to give the output its public name
        n = b.graph.node.add()
        n.op_type = "Identity"
        n.name = b.fresh("out")
        n.input.append(src)
        n.output.append(nm)
        b.value_info(b.graph.output, nm, ov.aval)
    b.graph.name = graph_name
    model = C["ModelProto"]()
    model.ir_version = 8
    model.producer_name = producer
    model.graph.CopyFrom(b.graph)
    ops = model.opset_import.add()
    ops.domain = ""
    ops.version = 17
    return model
