"""Official ONNX protobuf schema (FileDescriptorProto), vendored.

Provenance: extracted from the protobuf descriptor embedded in this
image's `torch/lib/libtorch_cpu.so` (PyTorch's bundled copy of the
official `onnx/onnx-ml.proto`, package-renamed `onnx_torch` by PyTorch's
build; field numbers and wire format are IDENTICAL to upstream ONNX, so
files serialized with these classes are standard .onnx files). Verified
against the official field numbering (ModelProto.graph=7,
GraphProto.node=1/initializer=5/input=11/output=12, NodeProto.op_type=4,
TensorProto.raw_data=9, AttributeProto.type=20, DataType FLOAT=1
INT64=7 BFLOAT16=16) at extraction time.

Why vendored: this image has google.protobuf but no `onnx` wheel, and no
network egress to fetch one — the descriptor IS the schema, so runtime
message classes are built from it directly.
"""
import base64 as _b64

_SCHEMA_B64 = (
    "Ch1vbm54L29ubnhfb25ueF90b3JjaC1tbC5wcm90bxIKb25ueF90b3JjaCKRBgoOQXR0cmlidXRl"
    "UHJvdG8SDAoEbmFtZRgBIAEoCRIVCg1yZWZfYXR0cl9uYW1lGBUgASgJEhIKCmRvY19zdHJpbmcY"
    "DSABKAkSNgoEdHlwZRgUIAEoDjIoLm9ubnhfdG9yY2guQXR0cmlidXRlUHJvdG8uQXR0cmlidXRl"
    "VHlwZRIJCgFmGAIgASgCEgkKAWkYAyABKAMSCQoBcxgEIAEoDBIiCgF0GAUgASgLMhcub25ueF90"
    "b3JjaC5UZW5zb3JQcm90bxIhCgFnGAYgASgLMhYub25ueF90b3JjaC5HcmFwaFByb3RvEjQKDXNw"
    "YXJzZV90ZW5zb3IYFiABKAsyHS5vbm54X3RvcmNoLlNwYXJzZVRlbnNvclByb3RvEiEKAnRwGA4g"
    "ASgLMhUub25ueF90b3JjaC5UeXBlUHJvdG8SDgoGZmxvYXRzGAcgAygCEgwKBGludHMYCCADKAMS"
    "DwoHc3RyaW5ncxgJIAMoDBIoCgd0ZW5zb3JzGAogAygLMhcub25ueF90b3JjaC5UZW5zb3JQcm90"
    "bxImCgZncmFwaHMYCyADKAsyFi5vbm54X3RvcmNoLkdyYXBoUHJvdG8SNQoOc3BhcnNlX3RlbnNv"
    "cnMYFyADKAsyHS5vbm54X3RvcmNoLlNwYXJzZVRlbnNvclByb3RvEioKC3R5cGVfcHJvdG9zGA8g"
    "AygLMhUub25ueF90b3JjaC5UeXBlUHJvdG8i2QEKDUF0dHJpYnV0ZVR5cGUSDQoJVU5ERUZJTkVE"
    "EAASCQoFRkxPQVQQARIHCgNJTlQQAhIKCgZTVFJJTkcQAxIKCgZURU5TT1IQBBIJCgVHUkFQSBAF"
    "EhEKDVNQQVJTRV9URU5TT1IQCxIOCgpUWVBFX1BST1RPEA0SCgoGRkxPQVRTEAYSCAoESU5UUxAH"
    "EgsKB1NUUklOR1MQCBILCgdURU5TT1JTEAkSCgoGR1JBUEhTEAoSEgoOU1BBUlNFX1RFTlNPUlMQ"
    "DBIPCgtUWVBFX1BST1RPUxAOSgQIDBANSgQIEBAUUgF2IpMBCg5WYWx1ZUluZm9Qcm90bxIMCgRu"
    "YW1lGAEgASgJEiMKBHR5cGUYAiABKAsyFS5vbm54X3RvcmNoLlR5cGVQcm90bxISCgpkb2Nfc3Ry"
    "aW5nGAMgASgJEjoKDm1ldGFkYXRhX3Byb3BzGAQgAygLMiIub25ueF90b3JjaC5TdHJpbmdTdHJp"
    "bmdFbnRyeVByb3RvIrMCCglOb2RlUHJvdG8SDQoFaW5wdXQYASADKAkSDgoGb3V0cHV0GAIgAygJ"
    "EgwKBG5hbWUYAyABKAkSDwoHb3BfdHlwZRgEIAEoCRIOCgZkb21haW4YByABKAkSEAoIb3Zlcmxv"
    "YWQYCCABKAkSLQoJYXR0cmlidXRlGAUgAygLMhoub25ueF90b3JjaC5BdHRyaWJ1dGVQcm90bxIS"
    "Cgpkb2Nfc3RyaW5nGAYgASgJEjoKDm1ldGFkYXRhX3Byb3BzGAkgAygLMiIub25ueF90b3JjaC5T"
    "dHJpbmdTdHJpbmdFbnRyeVByb3RvEkcKFWRldmljZV9jb25maWd1cmF0aW9ucxgKIAMoCzIoLm9u"
    "bnhfdG9yY2guTm9kZURldmljZUNvbmZpZ3VyYXRpb25Qcm90byIyChRJbnRJbnRMaXN0RW50cnlQ"
    "cm90bxILCgNrZXkYASABKAMSDQoFdmFsdWUYAiADKAMihgEKHE5vZGVEZXZpY2VDb25maWd1cmF0"
    "aW9uUHJvdG8SGAoQY29uZmlndXJhdGlvbl9pZBgBIAEoCRI0Cg1zaGFyZGluZ19zcGVjGAIgAygL"
    "Mh0ub25ueF90b3JjaC5TaGFyZGluZ1NwZWNQcm90bxIWCg5waXBlbGluZV9zdGFnZRgDIAEoBSKv"
    "AQoRU2hhcmRpbmdTcGVjUHJvdG8SEwoLdGVuc29yX25hbWUYASABKAkSDgoGZGV2aWNlGAIgAygD"
    "EkMKGWluZGV4X3RvX2RldmljZV9ncm91cF9tYXAYAyADKAsyIC5vbm54X3RvcmNoLkludEludExp"
    "c3RFbnRyeVByb3RvEjAKC3NoYXJkZWRfZGltGAQgAygLMhsub25ueF90b3JjaC5TaGFyZGVkRGlt"
    "UHJvdG8iWwoPU2hhcmRlZERpbVByb3RvEgwKBGF4aXMYASABKAMSOgoPc2ltcGxlX3NoYXJkaW5n"
    "GAIgAygLMiEub25ueF90b3JjaC5TaW1wbGVTaGFyZGVkRGltUHJvdG8iXAoVU2ltcGxlU2hhcmRl"
    "ZERpbVByb3RvEhMKCWRpbV92YWx1ZRgBIAEoA0gAEhMKCWRpbV9wYXJhbRgCIAEoCUgAEhIKCm51"
    "bV9zaGFyZHMYAyABKANCBQoDZGltIu4BChFUcmFpbmluZ0luZm9Qcm90bxIuCg5pbml0aWFsaXph"
    "dGlvbhgBIAEoCzIWLm9ubnhfdG9yY2guR3JhcGhQcm90bxIpCglhbGdvcml0aG0YAiABKAsyFi5v"
    "bm54X3RvcmNoLkdyYXBoUHJvdG8SQgoWaW5pdGlhbGl6YXRpb25fYmluZGluZxgDIAMoCzIiLm9u"
    "bnhfdG9yY2guU3RyaW5nU3RyaW5nRW50cnlQcm90bxI6Cg51cGRhdGVfYmluZGluZxgEIAMoCzIi"
    "Lm9ubnhfdG9yY2guU3RyaW5nU3RyaW5nRW50cnlQcm90byLGAwoKTW9kZWxQcm90bxISCgppcl92"
    "ZXJzaW9uGAEgASgDEjQKDG9wc2V0X2ltcG9ydBgIIAMoCzIeLm9ubnhfdG9yY2guT3BlcmF0b3JT"
    "ZXRJZFByb3RvEhUKDXByb2R1Y2VyX25hbWUYAiABKAkSGAoQcHJvZHVjZXJfdmVyc2lvbhgDIAEo"
    "CRIOCgZkb21haW4YBCABKAkSFQoNbW9kZWxfdmVyc2lvbhgFIAEoAxISCgpkb2Nfc3RyaW5nGAYg"
    "ASgJEiUKBWdyYXBoGAcgASgLMhYub25ueF90b3JjaC5HcmFwaFByb3RvEjoKDm1ldGFkYXRhX3By"
    "b3BzGA4gAygLMiIub25ueF90b3JjaC5TdHJpbmdTdHJpbmdFbnRyeVByb3RvEjQKDXRyYWluaW5n"
    "X2luZm8YFCADKAsyHS5vbm54X3RvcmNoLlRyYWluaW5nSW5mb1Byb3RvEiwKCWZ1bmN0aW9ucxgZ"
    "IAMoCzIZLm9ubnhfdG9yY2guRnVuY3Rpb25Qcm90bxI7Cg1jb25maWd1cmF0aW9uGBogAygLMiQu"
    "b25ueF90b3JjaC5EZXZpY2VDb25maWd1cmF0aW9uUHJvdG8iTQoYRGV2aWNlQ29uZmlndXJhdGlv"
    "blByb3RvEgwKBG5hbWUYASABKAkSEwoLbnVtX2RldmljZXMYAiABKAUSDgoGZGV2aWNlGAMgAygJ"
    "IjQKFlN0cmluZ1N0cmluZ0VudHJ5UHJvdG8SCwoDa2V5GAEgASgJEg0KBXZhbHVlGAIgASgJInEK"
    "EFRlbnNvckFubm90YXRpb24SEwoLdGVuc29yX25hbWUYASABKAkSSAoccXVhbnRfcGFyYW1ldGVy"
    "X3RlbnNvcl9uYW1lcxgCIAMoCzIiLm9ubnhfdG9yY2guU3RyaW5nU3RyaW5nRW50cnlQcm90byKE"
    "BAoKR3JhcGhQcm90bxIjCgRub2RlGAEgAygLMhUub25ueF90b3JjaC5Ob2RlUHJvdG8SDAoEbmFt"
    "ZRgCIAEoCRIsCgtpbml0aWFsaXplchgFIAMoCzIXLm9ubnhfdG9yY2guVGVuc29yUHJvdG8SOQoS"
    "c3BhcnNlX2luaXRpYWxpemVyGA8gAygLMh0ub25ueF90b3JjaC5TcGFyc2VUZW5zb3JQcm90bxIS"
    "Cgpkb2Nfc3RyaW5nGAogASgJEikKBWlucHV0GAsgAygLMhoub25ueF90b3JjaC5WYWx1ZUluZm9Q"
    "cm90bxIqCgZvdXRwdXQYDCADKAsyGi5vbm54X3RvcmNoLlZhbHVlSW5mb1Byb3RvEi4KCnZhbHVl"
    "X2luZm8YDSADKAsyGi5vbm54X3RvcmNoLlZhbHVlSW5mb1Byb3RvEj0KF3F1YW50aXphdGlvbl9h"
    "bm5vdGF0aW9uGA4gAygLMhwub25ueF90b3JjaC5UZW5zb3JBbm5vdGF0aW9uEjoKDm1ldGFkYXRh"
    "X3Byb3BzGBAgAygLMiIub25ueF90b3JjaC5TdHJpbmdTdHJpbmdFbnRyeVByb3RvSgQIAxAESgQI"
    "BBAFSgQIBhAKUgppcl92ZXJzaW9uUhBwcm9kdWNlcl92ZXJzaW9uUgxwcm9kdWNlcl90YWdSBmRv"
    "bWFpbiL1BgoLVGVuc29yUHJvdG8SDAoEZGltcxgBIAMoAxIRCglkYXRhX3R5cGUYAiABKAUSMAoH"
    "c2VnbWVudBgDIAEoCzIfLm9ubnhfdG9yY2guVGVuc29yUHJvdG8uU2VnbWVudBIWCgpmbG9hdF9k"
    "YXRhGAQgAygCQgIQARIWCgppbnQzMl9kYXRhGAUgAygFQgIQARITCgtzdHJpbmdfZGF0YRgGIAMo"
    "DBIWCgppbnQ2NF9kYXRhGAcgAygDQgIQARIMCgRuYW1lGAggASgJEhIKCmRvY19zdHJpbmcYDCAB"
    "KAkSEAoIcmF3X2RhdGEYCSABKAwSOQoNZXh0ZXJuYWxfZGF0YRgNIAMoCzIiLm9ubnhfdG9yY2gu"
    "U3RyaW5nU3RyaW5nRW50cnlQcm90bxI7Cg1kYXRhX2xvY2F0aW9uGA4gASgOMiQub25ueF90b3Jj"
    "aC5UZW5zb3JQcm90by5EYXRhTG9jYXRpb24SFwoLZG91YmxlX2RhdGEYCiADKAFCAhABEhcKC3Vp"
    "bnQ2NF9kYXRhGAsgAygEQgIQARI6Cg5tZXRhZGF0YV9wcm9wcxgQIAMoCzIiLm9ubnhfdG9yY2gu"
    "U3RyaW5nU3RyaW5nRW50cnlQcm90bxolCgdTZWdtZW50Eg0KBWJlZ2luGAEgASgDEgsKA2VuZBgC"
    "IAEoAyLJAgoIRGF0YVR5cGUSDQoJVU5ERUZJTkVEEAASCQoFRkxPQVQQARIJCgVVSU5UOBACEggK"
    "BElOVDgQAxIKCgZVSU5UMTYQBBIJCgVJTlQxNhAFEgkKBUlOVDMyEAYSCQoFSU5UNjQQBxIKCgZT"
    "VFJJTkcQCBIICgRCT09MEAkSCwoHRkxPQVQxNhAKEgoKBkRPVUJMRRALEgoKBlVJTlQzMhAMEgoK"
    "BlVJTlQ2NBANEg0KCUNPTVBMRVg2NBAOEg4KCkNPTVBMRVgxMjgQDxIMCghCRkxPQVQxNhAQEhAK"
    "DEZMT0FUOEU0TTNGThAREhIKDkZMT0FUOEU0TTNGTlVaEBISDgoKRkxPQVQ4RTVNMhATEhIKDkZM"
    "T0FUOEU1TTJGTlVaEBQSCQoFVUlOVDQQFRIICgRJTlQ0EBYSDgoKRkxPQVQ0RTJNMRAXIikKDERh"
    "dGFMb2NhdGlvbhILCgdERUZBVUxUEAASDAoIRVhURVJOQUwQASJ0ChFTcGFyc2VUZW5zb3JQcm90"
    "bxInCgZ2YWx1ZXMYASABKAsyFy5vbm54X3RvcmNoLlRlbnNvclByb3RvEigKB2luZGljZXMYAiAB"
    "KAsyFy5vbm54X3RvcmNoLlRlbnNvclByb3RvEgwKBGRpbXMYAyADKAMimwEKEFRlbnNvclNoYXBl"
    "UHJvdG8SMwoDZGltGAEgAygLMiYub25ueF90b3JjaC5UZW5zb3JTaGFwZVByb3RvLkRpbWVuc2lv"
    "bhpSCglEaW1lbnNpb24SEwoJZGltX3ZhbHVlGAEgASgDSAASEwoJZGltX3BhcmFtGAIgASgJSAAS"
    "EgoKZGVub3RhdGlvbhgDIAEoCUIHCgV2YWx1ZSLnBQoJVHlwZVByb3RvEjMKC3RlbnNvcl90eXBl"
    "GAEgASgLMhwub25ueF90b3JjaC5UeXBlUHJvdG8uVGVuc29ySAASNwoNc2VxdWVuY2VfdHlwZRgE"
    "IAEoCzIeLm9ubnhfdG9yY2guVHlwZVByb3RvLlNlcXVlbmNlSAASLQoIbWFwX3R5cGUYBSABKAsy"
    "GS5vbm54X3RvcmNoLlR5cGVQcm90by5NYXBIABI3Cg1vcHRpb25hbF90eXBlGAkgASgLMh4ub25u"
    "eF90b3JjaC5UeXBlUHJvdG8uT3B0aW9uYWxIABJAChJzcGFyc2VfdGVuc29yX3R5cGUYCCABKAsy"
    "Ii5vbm54X3RvcmNoLlR5cGVQcm90by5TcGFyc2VUZW5zb3JIABIzCgtvcGFxdWVfdHlwZRgHIAEo"
    "CzIcLm9ubnhfdG9yY2guVHlwZVByb3RvLk9wYXF1ZUgAEhIKCmRlbm90YXRpb24YBiABKAkaSAoG"
    "VGVuc29yEhEKCWVsZW1fdHlwZRgBIAEoBRIrCgVzaGFwZRgCIAEoCzIcLm9ubnhfdG9yY2guVGVu"
    "c29yU2hhcGVQcm90bxo0CghTZXF1ZW5jZRIoCgllbGVtX3R5cGUYASABKAsyFS5vbm54X3RvcmNo"
    "LlR5cGVQcm90bxpCCgNNYXASEAoIa2V5X3R5cGUYASABKAUSKQoKdmFsdWVfdHlwZRgCIAEoCzIV"
    "Lm9ubnhfdG9yY2guVHlwZVByb3RvGjQKCE9wdGlvbmFsEigKCWVsZW1fdHlwZRgBIAEoCzIVLm9u"
    "bnhfdG9yY2guVHlwZVByb3RvGk4KDFNwYXJzZVRlbnNvchIRCgllbGVtX3R5cGUYASABKAUSKwoF"
    "c2hhcGUYAiABKAsyHC5vbm54X3RvcmNoLlRlbnNvclNoYXBlUHJvdG8aJgoGT3BhcXVlEg4KBmRv"
    "bWFpbhgBIAEoCRIMCgRuYW1lGAIgASgJQgcKBXZhbHVlIjUKEk9wZXJhdG9yU2V0SWRQcm90bxIO"
    "CgZkb21haW4YASABKAkSDwoHdmVyc2lvbhgCIAEoAyKkAwoNRnVuY3Rpb25Qcm90bxIMCgRuYW1l"
    "GAEgASgJEg0KBWlucHV0GAQgAygJEg4KBm91dHB1dBgFIAMoCRIRCglhdHRyaWJ1dGUYBiADKAkS"
    "MwoPYXR0cmlidXRlX3Byb3RvGAsgAygLMhoub25ueF90b3JjaC5BdHRyaWJ1dGVQcm90bxIjCgRu"
    "b2RlGAcgAygLMhUub25ueF90b3JjaC5Ob2RlUHJvdG8SEgoKZG9jX3N0cmluZxgIIAEoCRI0Cgxv"
    "cHNldF9pbXBvcnQYCSADKAsyHi5vbm54X3RvcmNoLk9wZXJhdG9yU2V0SWRQcm90bxIOCgZkb21h"
    "aW4YCiABKAkSEAoIb3ZlcmxvYWQYDSABKAkSLgoKdmFsdWVfaW5mbxgMIAMoCzIaLm9ubnhfdG9y"
    "Y2guVmFsdWVJbmZvUHJvdG8SOgoObWV0YWRhdGFfcHJvcHMYDiADKAsyIi5vbm54X3RvcmNoLlN0"
    "cmluZ1N0cmluZ0VudHJ5UHJvdG9KBAgCEANKBAgDEARSDXNpbmNlX3ZlcnNpb25SBnN0YXR1cyqx"
    "AgoHVmVyc2lvbhISCg5fU1RBUlRfVkVSU0lPThAAEhkKFUlSX1ZFUlNJT05fMjAxN18xMF8xMBAB"
    "EhkKFUlSX1ZFUlNJT05fMjAxN18xMF8zMBACEhgKFElSX1ZFUlNJT05fMjAxN18xMV8zEAMSGAoU"
    "SVJfVkVSU0lPTl8yMDE5XzFfMjIQBBIYChRJUl9WRVJTSU9OXzIwMTlfM18xOBAFEhgKFElSX1ZF"
    "UlNJT05fMjAxOV85XzE5EAYSFwoTSVJfVkVSU0lPTl8yMDIwXzVfOBAHEhgKFElSX1ZFUlNJT05f"
    "MjAyMV83XzMwEAgSFwoTSVJfVkVSU0lPTl8yMDIzXzVfNRAJEhgKFElSX1ZFUlNJT05fMjAyNF8z"
    "XzI1EAoSDgoKSVJfVkVSU0lPThALKi4KDk9wZXJhdG9yU3RhdHVzEhAKDEVYUEVSSU1FTlRBTBAA"
    "EgoKBlNUQUJMRRAB"
)

_classes = None


def classes():
    """{message_name: class} for the ONNX schema (built once)."""
    global _classes
    if _classes is None:
        from google.protobuf import (
            descriptor_pb2, descriptor_pool, message_factory,
        )

        fd = descriptor_pb2.FileDescriptorProto()
        fd.ParseFromString(_b64.b64decode(_SCHEMA_B64))
        pool = descriptor_pool.DescriptorPool()
        pool.Add(fd)
        _classes = {}
        for m in fd.message_type:
            desc = pool.FindMessageTypeByName(f"{fd.package}.{m.name}")
            _classes[m.name] = message_factory.GetMessageClass(desc)
    return _classes


# TensorProto.DataType values (verified against the descriptor)
FLOAT = 1
UINT8 = 2
INT8 = 3
INT32 = 6
INT64 = 7
STRING = 8
BOOL = 9
FLOAT16 = 10
DOUBLE = 11
BFLOAT16 = 16
