"""paddle_tpu.onnx: model export.

Role parity: `paddle.onnx.export` (`python/paddle/onnx/export.py:22`, which
delegates to paddle2onnx). The TPU-native interchange format is serialized
StableHLO via `jax.export` — the artifact ONNX serves for the reference
(framework-neutral deployment). `export` therefore writes the StableHLO
artifact; true ONNX protobuf emission would need an onnx wheel, which this
image doesn't carry (gated with a clear error).
"""
from __future__ import annotations

import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None, format="stablehlo",
           **configs):
    if format == "onnx":
        raise NotImplementedError(
            "onnx protobuf emission needs the onnx package (not in this "
            "image); export format='stablehlo' produces the portable "
            "compiled artifact instead")
    if input_spec is None:
        raise ValueError("input_spec is required for export")
    from ..jit import save as jit_save

    jit_save(layer, path, input_spec=input_spec)
    return path + ".pdmodel"
