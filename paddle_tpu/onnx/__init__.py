"""paddle_tpu.onnx: model export — StableHLO and real ONNX emission.

Role parity: `paddle.onnx.export` (`python/paddle/onnx/export.py:22`,
which delegates to paddle2onnx). Two formats:

* ``format="stablehlo"`` (default): the TPU-native interchange artifact —
  serialized StableHLO via `jax.export` (`jit.save`), the deployment role
  ONNX plays for the reference.
* ``format="onnx"``: REAL ONNX protobuf emission. The layer's forward is
  traced to a jaxpr and converted op-by-op to an ONNX-17 graph
  (`_jaxpr_export.py`); the schema comes from the official ONNX
  descriptor vendored in `_schema.py` (field-number-identical to
  upstream, so the output is a standard ``.onnx`` file). Unsupported
  primitives raise loudly. `run_reference` evaluates an exported file
  with a bundled numpy evaluator so exports can be verified without an
  onnxruntime wheel.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export", "run_reference"]


def _trace_layer(layer, input_spec):
    import jax

    from ..core import flags
    from ..core.tensor import Tensor
    from ..static.framework import InputSpec

    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            shape = [1 if (d is None or d == -1) else int(d)
                     for d in s.shape]
            specs.append(jax.ShapeDtypeStruct(tuple(shape),
                                              np.dtype(s.dtype)))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s.shape),
                                              np.dtype(str(s.dtype))))
        else:
            a = np.asarray(s)
            specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

    def fn(*xs):
        with flags.trace_guard():
            out = layer(*[Tensor(x) for x in xs])
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        return [v._value if isinstance(v, Tensor) else v for v in leaves]

    return jax.make_jaxpr(fn)(*specs), specs


def export(layer, path, input_spec=None, opset_version=None,
           format="stablehlo", **configs):
    """Export `layer`. Returns the written model path.

    format="stablehlo": `path`.pdmodel via jit.save (compiled artifact).
    format="onnx":      `path`.onnx — real ONNX protobuf (see module doc).
    """
    if input_spec is None:
        raise ValueError("input_spec is required for export")
    if format == "onnx":
        from . import _jaxpr_export

        closed, specs = _trace_layer(layer, input_spec)
        model = _jaxpr_export.export_jaxpr(
            closed,
            arg_names=[f"input_{i}" for i in range(len(specs))],
            graph_name=type(layer).__name__,
        )
        out = path if path.endswith(".onnx") else path + ".onnx"
        with open(out, "wb") as f:
            f.write(model.SerializeToString())
        return out
    from ..jit import save as jit_save

    jit_save(layer, path, input_spec=input_spec)
    return path + ".pdmodel"


def run_reference(path, inputs):
    """Evaluate a saved .onnx file with the bundled numpy evaluator
    (export verification without onnxruntime)."""
    from ._runtime import run_reference as _run

    if isinstance(inputs, (list, tuple)):
        inputs = {f"input_{i}": np.asarray(v)
                  for i, v in enumerate(inputs)}
    return _run(path, {k: np.asarray(v) for k, v in inputs.items()})
