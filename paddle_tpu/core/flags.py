"""Global runtime flags and modes.

Role parity: `paddle/phi/core/flags.cc` (FLAGS_*) + dygraph/static mode
switches (`python/paddle/base/framework.py` in_dynamic_or_pir_mode). Here the
two modes are: eager (op-by-op with tape autograd) and trace (inside a
`jax.jit`/`jax.grad` transform, where autograd and fusion belong to XLA).
"""
from __future__ import annotations

import contextlib
import os
import threading


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.tracing = 0  # nesting depth of functional tracing
        self.static_mode = False  # paddle.enable_static() graph-build mode

_state = _State()


def is_grad_enabled() -> bool:
    return _state.grad_enabled and not _state.tracing


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = old


@contextlib.contextmanager
def enable_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = old


def in_trace() -> bool:
    return _state.tracing > 0


def in_static_mode() -> bool:
    return _state.static_mode and not _state.tracing


def set_static_mode(on: bool):
    _state.static_mode = bool(on)


@contextlib.contextmanager
def trace_guard():
    """Inside: ops run raw on jax values; no tape nodes are created."""
    _state.tracing += 1
    try:
        yield
    finally:
        _state.tracing -= 1


# --- FLAGS_* style runtime flags (paddle.set_flags parity) -------------------
def _env_bool(name, default="0"):
    return os.environ.get(name, default) in ("1", "true", "True")


_flags = {
    "FLAGS_check_nan_inf": _env_bool("FLAGS_check_nan_inf"),
    "FLAGS_eager_jit_ops": _env_bool("FLAGS_eager_jit_ops"),
    # kernel-granular degradation (VERDICT r2 task 3): a broken Pallas
    # kernel must cost speed, not the whole datapoint. The master flag
    # disables the entire tier; per-kernel flags disable one dispatch site.
    "FLAGS_disable_pallas": _env_bool("FLAGS_disable_pallas"),
    "FLAGS_disable_pallas_flash": _env_bool("FLAGS_disable_pallas_flash"),
    "FLAGS_disable_pallas_fused_norm": _env_bool("FLAGS_disable_pallas_fused_norm"),
    # (ring attention is jnp/lax collectives, not pallas_call — no flag)
    "FLAGS_disable_pallas_rope": _env_bool("FLAGS_disable_pallas_rope"),
    "FLAGS_disable_pallas_decode": _env_bool("FLAGS_disable_pallas_decode"),
    # fused vision kernels (ISSUE 10): Swin window attention and the
    # conv+norm+act inference fusion
    "FLAGS_disable_pallas_window_attn": _env_bool(
        "FLAGS_disable_pallas_window_attn"),
    "FLAGS_disable_pallas_conv_norm": _env_bool(
        "FLAGS_disable_pallas_conv_norm"),
    "FLAGS_use_autotune": _env_bool("FLAGS_use_autotune", "1"),
    # force the expanded-KV MHA kernels for GQA attention (grouped is
    # the default: less KV HBM traffic; the round-5 on-chip A/B showed
    # backward can favor expanded at some block shapes — PERF.md)
    "FLAGS_flash_gqa_expand": _env_bool("FLAGS_flash_gqa_expand"),
    # Extra scoped-VMEM budget for Pallas kernels (KiB, 0 = compiler
    # default of 16 MiB). The round-5 kv-native flash kernels keep all
    # heads' intermediates on the Mosaic stack and need ~32-64 MiB at
    # training block sizes; v5e has 128 MiB VMEM, so raising the limit
    # is real headroom, not overcommit. Applied via jit compiler_options
    # at the train-step jit sites (the local XLA_FLAGS parser rejects
    # TPU-only flags on a CPU-built jaxlib, so env XLA_FLAGS cannot
    # carry it).
    "FLAGS_scoped_vmem_limit_kib": int(
        os.environ.get("FLAGS_scoped_vmem_limit_kib", "0")),
}


def jit_compiler_options():
    """Per-jit XLA compiler options implied by flags (None when empty):
    pass as jax.jit(..., compiler_options=...) at hot jit sites."""
    lim = _flags.get("FLAGS_scoped_vmem_limit_kib") or 0
    if lim:
        return {"xla_tpu_scoped_vmem_limit_kib": int(lim)}
    return None


def pallas_enabled(kernel: str) -> bool:
    """Dispatch-site gate for one Pallas kernel ('flash', 'fused_norm',
    'rope', 'ring', 'decode', 'window_attn', 'conv_norm')."""
    return not (_flags.get("FLAGS_disable_pallas")
                or _flags.get(f"FLAGS_disable_pallas_{kernel}"))


def set_flags(d: dict):
    _flags.update(d)


def get_flags(keys=None):
    if keys is None:
        return dict(_flags)
    if isinstance(keys, str):
        return {keys: _flags.get(keys)}
    return {k: _flags.get(k) for k in keys}
