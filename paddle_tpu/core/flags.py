"""Global runtime flags and modes.

Role parity: `paddle/phi/core/flags.cc` (FLAGS_*) + dygraph/static mode
switches (`python/paddle/base/framework.py` in_dynamic_or_pir_mode). Here the
two modes are: eager (op-by-op with tape autograd) and trace (inside a
`jax.jit`/`jax.grad` transform, where autograd and fusion belong to XLA).
"""
from __future__ import annotations

import contextlib
import os
import threading


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.tracing = 0  # nesting depth of functional tracing
        self.static_mode = False  # paddle.enable_static() graph-build mode

_state = _State()


def is_grad_enabled() -> bool:
    return _state.grad_enabled and not _state.tracing


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = old


@contextlib.contextmanager
def enable_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = old


def in_trace() -> bool:
    return _state.tracing > 0


def in_static_mode() -> bool:
    return _state.static_mode and not _state.tracing


def set_static_mode(on: bool):
    _state.static_mode = bool(on)


@contextlib.contextmanager
def trace_guard():
    """Inside: ops run raw on jax values; no tape nodes are created."""
    _state.tracing += 1
    try:
        yield
    finally:
        _state.tracing -= 1


# --- FLAGS_* style runtime flags (paddle.set_flags parity) -------------------
_flags = {
    "FLAGS_check_nan_inf": os.environ.get("FLAGS_check_nan_inf", "0") in ("1", "true", "True"),
    "FLAGS_eager_jit_ops": os.environ.get("FLAGS_eager_jit_ops", "0") in ("1", "true", "True"),
}


def set_flags(d: dict):
    _flags.update(d)


def get_flags(keys=None):
    if keys is None:
        return dict(_flags)
    if isinstance(keys, str):
        return {keys: _flags.get(keys)}
    return {k: _flags.get(k) for k in keys}
