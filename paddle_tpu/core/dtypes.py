"""Dtype registry and default-dtype management.

Role parity: paddle dtype surface (`paddle/phi/common/data_type.h`,
`python/paddle/framework/dtype.py`). TPU-first: bfloat16 is a first-class
dtype; float64 is discouraged (XLA TPU demotes it) but supported on CPU.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

# Canonical dtypes (jnp dtype objects)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "fp16": float16,
    "fp32": float32,
    "fp64": float64,
}

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize a user-provided dtype (str / np / jnp) to a jnp dtype.

    TPU-first canonicalization: with jax x64 disabled (the TPU default),
    int64/float64 requests map to int32/float32 — the same demotion XLA
    performs, applied here silently so the paddle-style `int64` default
    index dtype works without per-op warnings."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _STR2DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        dt = _STR2DTYPE[key]
    else:
        dt = jnp.dtype(dtype)
    import jax

    if not jax.config.jax_enable_x64:
        dt = jnp.dtype(dt)
        if dt == jnp.dtype(np.int64):
            return jnp.int32
        if dt == jnp.dtype(np.float64):
            return jnp.float32
        if dt == jnp.dtype(np.uint64):
            return jnp.uint32
        if dt == jnp.dtype(np.complex128):
            return jnp.complex64
    return dt


def get_default_dtype():
    return _default_dtype


def set_default_dtype(dtype):
    global _default_dtype
    dtype = convert_dtype(dtype)
    if dtype not in (float16, bfloat16, float32, float64):
        raise TypeError(f"Default dtype must be floating, got {dtype}")
    _default_dtype = dtype


@contextlib.contextmanager
def default_dtype_guard(dtype):
    old = get_default_dtype()
    set_default_dtype(dtype)
    try:
        yield
    finally:
        set_default_dtype(old)


def is_floating_point(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), np.floating)


def is_integer(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)


def is_complex(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), np.complexfloating)
