"""Eager Tensor: a mutable handle over an immutable jax.Array.

Role parity: `paddle::Tensor` + eager `AutogradMeta`
(`paddle/phi/api/include/tensor.h:82`, `paddle/fluid/eager/autograd_meta.h`)
and the Python Tensor surface (`paddle/fluid/pybind/eager_method.cc`).

TPU-first: the payload is always a jax.Array (device-resident, async) or a
jax tracer (inside functional transforms) — mutation (`x[i]=v`, `add_`)
rebinds the handle to a new functional value, which XLA turns back into
in-place buffer updates via donation under jit.

Math/manipulation methods are patched onto this class by `paddle_tpu.ops`
(mirroring how the reference patches `python/paddle/tensor/` methods onto the
pybind Tensor).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import dtypes as _dtypes


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_hooks",
        "name",
        "persistable",
        "dist_attr",
        "__weakref__",
    )

    def __init__(self, value, dtype=None, stop_gradient=True, name=None):
        if isinstance(value, Tensor):
            value = value._value
        dtype = _dtypes.convert_dtype(dtype)
        if not isinstance(value, jax.Array) and not _is_tracer(value):
            if isinstance(value, (bool, int, float, list, tuple, np.ndarray)):
                arr = np.asarray(value)
                if dtype is None and arr.dtype == np.float64:
                    arr = arr.astype(np.dtype(_dtypes.get_default_dtype()))
                value = jnp.asarray(arr, dtype=dtype)
            else:
                value = jnp.asarray(value, dtype=dtype)
        elif dtype is not None and value.dtype != jnp.dtype(dtype):
            value = value.astype(dtype)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._hooks = []
        self.name = name
        self.persistable = False
        self.dist_attr = None  # (mesh, placements) slot for auto-parallel

    # --- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        try:
            devs = self._value.devices()
            return next(iter(devs))
        except Exception:
            return None

    @property
    def T(self):
        from .. import ops

        return ops.transpose(self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from .. import ops

        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return ops.transpose(self, perm)

    @property
    def is_leaf(self):
        return self._grad_node is None

    # --- grad ---------------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def _accumulate_grad(self, gval):
        if isinstance(gval, Tensor):
            gval = gval._value
        if gval.dtype != self._value.dtype:
            gval = gval.astype(self._value.dtype)
        if self._grad is None:
            self._grad = Tensor(gval, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._value + gval, stop_gradient=True)

    def backward(self, grad_tensor=None, retain_graph=False):
        from . import engine

        engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Hook fires on this tensor's gradient during backward (leaf or not)."""
        if self._grad_node is None:
            self._hooks.append(hook)

            def remove():
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        else:
            node, idx = self._grad_node
            node.out_hooks.setdefault(idx, []).append(hook)

            def remove():
                try:
                    node.out_hooks[idx].remove(hook)
                except (KeyError, ValueError):
                    pass

        return _HookRemover(remove)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        t.dist_attr = self.dist_attr
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from .. import ops

        return ops.assign(self)

    # --- host interop -------------------------------------------------------
    # _force_hook (jit.sot capture): observes every point where a concrete
    # value leaves tensor-land — each is a graph break + branch guard in
    # the SOT tier (reference sot/opcode_translator BreakGraphError sites)
    _force_hook = None

    @classmethod
    def _set_force_hook(cls, fn):
        cls._force_hook = fn

    def _forced(self, kind, value):
        hook = Tensor._force_hook
        if hook is not None:
            hook(self, kind, value)
        return value

    def numpy(self):
        return self._forced("value", np.asarray(self._value))

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __dlpack__(self, *a, **kw):
        return self._value.__dlpack__(*a, **kw)

    # --- dtype/device movement ---------------------------------------------
    def astype(self, dtype):
        from .. import ops

        return ops.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs):
        # accepts dtype strings / device strings; device moves are no-ops on
        # the single-controller jax runtime (placement is sharding-driven)
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a.lower() in (
                "cpu", "gpu", "tpu", "xpu", "device",
            ) or ":" in str(a):
                continue
            try:
                dt = _dtypes.convert_dtype(a)
            except (ValueError, TypeError):
                continue
            if dt is not None:
                out = out.astype(dt)
        return out

    def cpu(self):
        return self

    def cuda(self, *a, **kw):
        return self

    def pin_memory(self):
        return self

    # --- mutation (functional rebind) ---------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value, dtype=self._value.dtype)
        return self

    def _rebind(self, other):
        """Adopt another tensor's value + grad linkage (in-place op result)."""
        self._value = other._value
        self._grad_node = other._grad_node
        self.stop_gradient = other.stop_gradient
        return self

    def __setitem__(self, index, value):
        from .. import ops

        index = _unwrap_index(index)
        self._rebind(ops.index_put(self, index, value))

    def __getitem__(self, index):
        from .. import ops

        return ops.getitem(self, _unwrap_index(index))

    # --- python protocol ----------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self._value.dtype}{grad_info},\n"
            f"       {np.asarray(self._value)!r})"
        )

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a multi-element Tensor is ambiguous")
        return self._forced("bool", bool(np.asarray(self._value)))

    def __int__(self):
        return self._forced("int", int(np.asarray(self._value)))

    def __float__(self):
        return self._forced("float", float(np.asarray(self._value)))

    def __index__(self):
        return self._forced("int", int(np.asarray(self._value)))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)

    # numpy interop (lets np.asarray(tensor) work)
    def __array__(self, dtype=None):
        a = self._forced("value", np.asarray(self._value))
        return a.astype(dtype) if dtype is not None else a

    def to_sparse_coo(self, sparse_dim=None):
        from ..sparse import to_sparse_coo_from_dense

        return to_sparse_coo_from_dense(self, sparse_dim=sparse_dim)

    def to_sparse_csr(self):
        return self.to_sparse_coo(sparse_dim=2).to_sparse_csr()

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return False

    def element_size(self):
        return self._value.dtype.itemsize

    def dim(self):
        return self.ndim

    def numel(self):
        return self.size

    def block_until_ready(self):
        if hasattr(self._value, "block_until_ready"):
            self._value.block_until_ready()
        return self


class _HookRemover:
    def __init__(self, fn):
        self._fn = fn

    def remove(self):
        self._fn()


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _unwrap_index(index):
    def u(i):
        return i._value if isinstance(i, Tensor) else i

    if isinstance(index, tuple):
        return tuple(u(i) for i in index)
    return u(index)


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable, optionally carrying
    a named-sharding placement for the distributed recipes (~ DistAttr slot on
    paddle's EagerParamBase, `python/paddle/base/framework.py`)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
