"""Op dispatch: the single gate every op goes through.

Role parity: the generated `*_ad_func` eager forwards + C++ API dispatch of
the reference (`paddle/fluid/eager/auto_code_generator/generator/eager_gen.py`,
`paddle/phi/api/yaml/generator/api_base.py` — select kernel, PrepareData,
InferMeta, launch, then build the grad node). TPU-first collapse: the "kernel"
is a pure jnp/lax/pallas function; shape-dtype inference, lowering, and fusion
are XLA's job; the grad node's backward fn is the op's `jax.vjp` closure.

Three modes:
  * trace  — inside `jit.to_static`/functional transforms: run raw on tracers.
  * eager, no grad needed — run raw, wrap output.
  * eager, grad — run under `jax.vjp` over the floating Tensor inputs and
    record a GradNode edge-wired into the producing nodes of its inputs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import flags
from .engine import GradNode
from .tensor import Tensor

_amp_cast_hook = None  # installed by paddle_tpu.amp
_op_stats_sink = None  # installed by amp.debugging op-stats collection
_sot_recorder = None   # installed by jit.sot during eager capture


def set_amp_cast_hook(fn):
    global _amp_cast_hook
    _amp_cast_hook = fn


def set_sot_recorder(fn):
    """fn(name, raw_fn, args, kwargs, out) called after each dispatched op
    (jit.sot eager-capture tier), or None to disable."""
    global _sot_recorder
    _sot_recorder = fn


def set_op_stats_sink(sink):
    """sink: dict[(op_name, dtype_str)] -> count, or None to disable."""
    global _op_stats_sink
    _op_stats_sink = sink


def _record_op_stats(sink, name, out):
    leaves = jax.tree_util.tree_flatten(out)[0]
    for leaf in leaves:
        if hasattr(leaf, "dtype"):
            key = (name, str(leaf.dtype))
            sink[key] = sink.get(key, 0) + 1


def _is_tensor(x):
    return isinstance(x, Tensor)


def _flatten(args, kwargs):
    return jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)


def apply(name, fn, *args, **kwargs):
    """Run `fn` (pure over jax arrays) on args that may contain Tensors
    anywhere in their pytree structure; returns Tensor-wrapped outputs with
    the grad graph extended when needed."""
    if _amp_cast_hook is not None:
        args, kwargs = _amp_cast_hook(name, args, kwargs)
    if flags.in_static_mode():
        from ..static import recorder

        if recorder.should_record(args, kwargs):
            return recorder.record(name, fn, args, kwargs)
    leaves, treedef = _flatten(args, kwargs)
    tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

    track = False
    if tensor_pos and flags.is_grad_enabled():
        track = any(not leaves[i].stop_gradient for i in tensor_pos)

    if not track:
        vals = [l._value if isinstance(l, Tensor) else l for l in leaves]
        a, kw = jax.tree_util.tree_unflatten(treedef, vals)
        out = fn(*a, **kw)
        if _watching():
            check_nan_inf(name, out)
        sink = _op_stats_sink
        if sink is not None and not flags.in_trace():
            _record_op_stats(sink, name, out)
        if flags.in_trace():
            # grad bookkeeping belongs to jax here; just propagate the flag
            sg = not any(not leaves[i].stop_gradient for i in tensor_pos)
        else:
            sg = True
        wrapped = _wrap_outputs(out, stop_gradient=sg)
        if _sot_recorder is not None:
            _sot_recorder(name, fn, args, kwargs, wrapped)
        return wrapped

    # --- autograd path ---
    diff_pos = [
        i for i in tensor_pos
        if not leaves[i].stop_gradient and jnp.issubdtype(leaves[i]._value.dtype, np.inexact)
    ]
    base_vals = [l._value if isinstance(l, Tensor) else l for l in leaves]

    def pure(*dvals):
        cur = list(base_vals)
        for p, v in zip(diff_pos, dvals):
            cur[p] = v
        a, kw = jax.tree_util.tree_unflatten(treedef, cur)
        return fn(*a, **kw)

    diff_vals = [base_vals[p] for p in diff_pos]
    out, vjp_fn = jax.vjp(pure, *diff_vals)
    if _watching():
        check_nan_inf(name, out)
    sink = _op_stats_sink
    if sink is not None:
        _record_op_stats(sink, name, out)

    out_leaves, out_tree = jax.tree_util.tree_flatten(out)
    edges = []
    for p in diff_pos:
        t = leaves[p]
        if t._grad_node is not None:
            edges.append(("node", t._grad_node[0], t._grad_node[1]))
        else:
            edges.append(("leaf", t))
    out_avals = [(tuple(o.shape), o.dtype) for o in out_leaves]
    node = GradNode(name, _VjpAdapter(vjp_fn, out_tree), edges,
                    len(out_leaves), out_avals,
                    pure_fn=pure,
                    input_tensors=[leaves[p] for p in diff_pos])

    wrapped = []
    for i, o in enumerate(out_leaves):
        t = Tensor(o, stop_gradient=not jnp.issubdtype(o.dtype, np.inexact))
        if not t.stop_gradient:
            t._grad_node = (node, i)
        wrapped.append(t)
    result = jax.tree_util.tree_unflatten(out_tree, wrapped)
    if _sot_recorder is not None:
        _sot_recorder(name, fn, args, kwargs, result)
    return result


class _VjpAdapter:
    """Adapts flat cotangent list -> jax.vjp cotangent pytree -> flat grads."""

    __slots__ = ("vjp_fn", "out_tree")

    def __init__(self, vjp_fn, out_tree):
        self.vjp_fn = vjp_fn
        self.out_tree = out_tree

    def __call__(self, cots):
        if not isinstance(cots, (tuple, list)):
            cots = (cots,)
        cot_tree = jax.tree_util.tree_unflatten(self.out_tree, list(cots))
        return self.vjp_fn(cot_tree)


def check_nan_inf(name, out):
    """FLAGS_check_nan_inf watcher (parity: eager nan/inf hook
    `paddle/fluid/eager/nan_inf_utils.h` checking every kernel output).
    Debug tool: forces a device sync per op, exactly as the reference's
    flag does."""
    leaves = jax.tree_util.tree_flatten(out)[0]
    for i, leaf in enumerate(leaves):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                leaf.dtype, np.inexact):
            continue
        bad = ~np.asarray(jnp.isfinite(leaf)).all()
        if bad:
            arr = np.asarray(leaf)
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            raise FloatingPointError(
                f"op {name!r} output {i} contains {n_nan} NaN / {n_inf} Inf "
                f"values (shape={arr.shape}, dtype={arr.dtype}) — "
                "FLAGS_check_nan_inf watcher")


def _watching():
    # hot path: direct dict read, no allocation
    return flags._flags["FLAGS_check_nan_inf"] and not flags.in_trace()


def _wrap_outputs(out, stop_gradient):
    def w(o):
        if isinstance(o, Tensor):
            return o
        return Tensor(o, stop_gradient=stop_gradient)

    return jax.tree_util.tree_map(w, out)


def op(name=None):
    """Decorator turning a pure-jnp function into an eager framework op."""

    def deco(fn):
        opname = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return apply(opname, fn, *args, **kwargs)

        wrapper.raw = fn  # the pure function, for jit/functional paths
        wrapper.op_name = opname
        return wrapper

    return deco
