from . import dtypes, engine, flags, rng  # noqa: F401
from .tensor import Parameter, Tensor  # noqa: F401
