"""Lazy, version-tolerant access to ``jax.export``.

The container's baked-in jax can predate the public `jax.export` module
(it moved out of `jax.experimental.export` around 0.4.30, and some
builds strip it).  A top-level ``import jax.export`` therefore used to
kill module import — and with it pytest collection — for the whole
static/onnx/inference chain.  Every export consumer now resolves the
module through here at CALL time:

    from ..core.export_compat import get_jax_export
    je = get_jax_export()          # raises ExportUnavailableError
    exp = je.export(jax.jit(fn))(*specs)

Import of the consumer modules never touches jax.export; tests gate on
`jax_export_available()` and skip with a reason instead of dying at
collection.
"""
from __future__ import annotations

__all__ = ["ExportUnavailableError", "get_jax_export",
           "jax_export_available"]


class ExportUnavailableError(ImportError):
    """This jax build has no usable jax.export module."""


_module = None
_error = None


def get_jax_export():
    """The jax.export module (new or experimental spelling), cached.
    Raises ExportUnavailableError with an actionable message when the
    build lacks both."""
    global _module, _error
    if _module is not None:
        return _module
    if _error is not None:
        raise ExportUnavailableError(_error)
    import jax

    try:
        import jax.export as je
    except ImportError:
        je = None
    if je is None or not hasattr(je, "export"):
        try:
            from jax.experimental import export as je  # pre-0.4.30 home
        except ImportError:
            je = None
    if je is not None and hasattr(je, "export"):
        _module = je
        return je
    _error = (
        f"this jax build ({jax.__version__}) provides no usable "
        "jax.export module (neither jax.export nor "
        "jax.experimental.export): serialized-StableHLO paths — "
        "jit.save with input_spec, jit.load, "
        "static.save/load_inference_model, onnx stablehlo format — "
        "are unavailable; parameter-only save/load still works")
    raise ExportUnavailableError(_error)


def jax_export_available() -> bool:
    """True when get_jax_export() would succeed (tests use this for
    skip-with-reason instead of dying at collection)."""
    try:
        get_jax_export()
        return True
    except ExportUnavailableError:
        return False
