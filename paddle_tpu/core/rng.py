"""RNG state: functional PRNG keys behind a stateful generator facade.

Role parity: `phi::Generator` (paddle/phi/core/generator.h) + `paddle.seed`.
TPU-first: the state is a jax PRNG key (threefry), so a generator can be
captured as an implicit input/output of a traced program (the jit layer does
exactly that), keeping randomness correct and reproducible under compilation —
the role paddle's TP RNG tracker (`fleet/layers/mpu/random.py`) plays is
covered by deriving per-mesh-axis keys via fold_in.
"""
from __future__ import annotations

import jax


class Generator:
    """Key creation is lazy: no device computation happens at import time
    (backend init is deferred to first real use)."""

    def __init__(self, seed=0):
        self._key = None
        self._seed = seed

    def manual_seed(self, seed):
        self._key = None
        self._seed = seed
        return self

    seed = manual_seed

    def initial_seed(self):
        return self._seed

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def get_state(self):
        return self.key

    def set_state(self, key):
        self._key = key

    def split(self):
        """Return a fresh subkey; advances the internal key (works under
        trace: the key becomes a tracer that the jit layer threads through)."""
        self._key, sub = jax.random.split(self.key)
        return sub

    def fold_in(self, data):
        return jax.random.fold_in(self.key, data)


default_generator = Generator(0)


class _DeferredKey:
    """Marker: resolve the key when the op body actually runs (static-mode
    replay), not at record time."""

    __slots__ = ()


_DEFERRED = _DeferredKey()


class OpKey:
    """A PRNG key passed as an op argument, tagged so capture tiers can
    recognize it structurally (legacy uint32[2] keys are indistinguishable
    from data by dtype): the SOT tier substitutes a per-call fold_in of a
    threaded key here, which is what makes dropout resample across compiled
    replays instead of baking the capture-time mask."""

    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key


def split_for_op():
    """Key for a random op body. Eager/trace: split NOW at dispatch — the
    concrete key is captured by the op's pure fn, so vjp re-evaluation
    (create_graph, double grad) replays the SAME randomness. Static mode:
    defer — each Executor.run replay draws from the per-run threaded key, so
    masks resample across runs (the reference's seed/offset op attributes
    serve the same two purposes)."""
    from . import flags

    if flags.in_static_mode():
        return _DEFERRED
    return OpKey(default_generator.split())


def materialize(key):
    """First line of a random op body: resolve a possibly-deferred key."""
    if isinstance(key, OpKey):
        return key.key
    if isinstance(key, _DeferredKey):
        return default_generator.split()
    return key


def seed(s):
    default_generator.manual_seed(int(s))
    return default_generator


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(states):
    default_generator.set_state(states[0])
