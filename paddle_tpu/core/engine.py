"""Eager autograd engine: grad-graph nodes + queue-based backward walk.

Role parity with the reference's eager autograd runtime:
  - GradNode            ~ `GradNodeBase` (paddle/fluid/eager/grad_node_info.h:197)
  - run_backward        ~ `egr::RunBackward` (paddle/fluid/eager/backward.cc:105)
    (same design: build an in-degree map over the reachable grad graph, then a
    ready-queue reverse-topological walk accumulating cotangents per node)
  - grad()              ~ partial-grad `general_grad.h` path (paddle.grad)
  - leaf accumulation   ~ `GradNodeAccumulation` + gradient hooks, which is the
    DataParallel reducer hook point in the reference (backward.cc stack §3.2).

TPU-first design: each node's backward function is the `jax.vjp` closure of the
op's pure-jnp forward, so every backward step is itself an XLA computation and
`create_graph=True` (double grad) falls out by re-entering the dispatch layer
when calling the vjp.
"""
from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp



class GradNode:
    """One differentiable op application in the eager grad graph."""

    __slots__ = (
        "name",
        "vjp_fn",
        "edges",
        "n_outputs",
        "out_avals",
        "out_hooks",
        "released",
        "pure_fn",
        "input_tensors",
        "__weakref__",
    )

    def __init__(self, name, vjp_fn, edges, n_outputs, out_avals,
                 pure_fn=None, input_tensors=None):
        self.name = name
        self.vjp_fn = vjp_fn
        # edges[i] describes where grad w.r.t. diff-input i flows:
        #   ("leaf", tensor)          -> accumulate into tensor.grad
        #   ("node", prev_node, idx)  -> contributes cotangent idx of prev_node
        self.edges = edges
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.out_hooks = {}  # out_index -> [hook fns] (intermediate tensor hooks)
        self.released = False
        # For create_graph (higher-order AD): the op's pure function over its
        # differentiable inputs + the input Tensors themselves (~ the saved
        # TensorWrappers of a reference grad node). jax.vjp closures treat
        # primals as constants, so grad-of-grad re-derives the vjp from
        # pure_fn through the dispatch gate instead.
        self.pure_fn = pure_fn
        self.input_tensors = input_tensors

    def zero_cotangent(self, i):
        shape, dtype = self.out_avals[i]
        if not jnp.issubdtype(dtype, jnp.inexact):
            # jax.vjp expects float0 cotangents for non-differentiable outputs
            import numpy as np

            return np.zeros(shape, jax.dtypes.float0)
        return jnp.zeros(shape, dtype)

    def run_vjp(self, out_cots, create_graph=False):
        """Call the stored vjp closure; under create_graph the call is routed
        through the dispatch layer so the backward computation itself gets a
        grad graph (higher-order AD, ~ generated higher-order GradNodes in the
        reference)."""
        cots = [
            c if c is not None else self.zero_cotangent(i)
            for i, c in enumerate(out_cots)
        ]
        if create_graph and self.pure_fn is not None:
            from . import dispatch

            pure_fn = self.pure_fn
            out_tree = self.vjp_fn.out_tree

            def gradfn(primals, cot_leaves):
                _, vjp = jax.vjp(pure_fn, *primals)
                cot_tree = jax.tree_util.tree_unflatten(out_tree,
                                                        list(cot_leaves))
                return vjp(cot_tree)

            return dispatch.apply(f"{self.name}_grad", gradfn,
                                  list(self.input_tensors), cots)
        if getattr(self.vjp_fn, "wants_tensors", False):
            # PyLayer-style: the backward is user python over Tensors
            return self.vjp_fn(cots, create_graph)
        vals = [c._value if hasattr(c, "_value") else c for c in cots]
        return self.vjp_fn(vals)

    def release(self):
        self.vjp_fn = None
        self.pure_fn = None
        self.input_tensors = None
        self.released = True

    def __repr__(self):
        return f"<GradNode {self.name} n_out={self.n_outputs}>"


def _zeros_like_value(v):
    return jnp.zeros(v.shape, v.dtype)


def _build_indegree(roots):
    """BFS the grad graph; count, per node, how many downstream node-edges feed it."""
    indeg = {}
    seen = set()
    q = deque()
    for n in roots:
        if id(n) not in seen:
            seen.add(id(n))
            indeg.setdefault(id(n), 0)
            q.append(n)
    nodes = {id(n): n for n in roots}
    while q:
        n = q.popleft()
        for edge in n.edges:
            if edge[0] == "node":
                prev = edge[1]
                indeg[id(prev)] = indeg.get(id(prev), 0) + 1
                nodes[id(prev)] = prev
                if id(prev) not in seen:
                    seen.add(id(prev))
                    q.append(prev)
    return indeg, nodes


class _CotangentBuffer:
    """Per-node accumulation of output cotangents (GradTensorHolder parity)."""

    def __init__(self):
        self.buf = {}  # id(node) -> {out_idx: value}

    def add(self, node, idx, value):
        slot = self.buf.setdefault(id(node), {})
        if idx in slot:
            slot[idx] = slot[idx] + value
        else:
            slot[idx] = value

    def pop(self, node, out_shapes=None):
        return self.buf.pop(id(node), {})


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 create_graph=False, inputs=None, accumulate=True):
    """Reverse-topological walk from output tensors.

    If `inputs` is given (paddle.grad path), returns grads for exactly those
    tensors (accumulating into .grad only when accumulate=True and inputs is
    None, matching Tensor.backward semantics).
    """
    from .tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Seed cotangents.
    cot = _CotangentBuffer()
    roots = []
    leaf_seed = {}  # id(tensor) -> seed grad for roots that are leaves
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                f"Tensor {t.name or ''} has stop_gradient=True; cannot backward."
            )
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward roots; "
                    f"got shape {tuple(t.shape)}"
                )
            gval = jnp.ones(t._value.shape, t._value.dtype)
        else:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if t._grad_node is not None:
            node, idx = t._grad_node
            if node.released:
                raise RuntimeError(
                    "Trying to backward through the graph a second time after it "
                    "was freed. Specify retain_graph=True on the first backward."
                )
            cot.add(node, idx, gval)
            roots.append(node)
        else:
            leaf_seed[id(t)] = (t, gval)

    indeg, _nodes = _build_indegree(roots)

    # Target collection for paddle.grad(): map (node,idx)->slot and leaf ids.
    want_by_nodeidx = {}
    want_by_leaf = {}
    results = None
    if inputs is not None:
        results = [None] * len(inputs)
        for i, t in enumerate(inputs):
            if t._grad_node is not None:
                want_by_nodeidx.setdefault((id(t._grad_node[0]), t._grad_node[1]), []).append(i)
            else:
                want_by_leaf.setdefault(id(t), []).append(i)
            # root tensor may itself be an input
            if id(t) in leaf_seed:
                results[i] = leaf_seed[id(t)][1]

    def _emit_leaf(tensor, gval):
        for hook in tensor._hooks:
            out = hook(_wrap(gval))
            if out is not None:
                gval = out
        if inputs is not None:
            for i in want_by_leaf.get(id(tensor), ()):
                results[i] = gval if results[i] is None else results[i] + gval
            if not accumulate:
                return
        if tensor.stop_gradient:
            return
        if inputs is None or accumulate:
            tensor._accumulate_grad(gval)

    def _wrap(gval):
        if isinstance(gval, Tensor):
            return gval
        return Tensor(gval, stop_gradient=True)

    # Leaves that were direct roots.
    for t, gval in leaf_seed.values():
        _emit_leaf(t, gval)

    ready = deque(n for n in _nodes.values() if indeg.get(id(n), 0) == 0)
    processed = set()
    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        cots = cot.pop(node)
        if node.released:
            raise RuntimeError(
                "Grad graph was already freed; use retain_graph=True.")
        # Assemble full cotangent tuple (zeros for unused outputs).
        out_cots = []
        for i in range(node.n_outputs):
            v = cots.get(i)
            if v is None:
                v = node.zero_cotangent(i) if hasattr(node, "zero_cotangent") else None
            out_cots.append(v)
        # Fire intermediate-tensor hooks.
        for i, hooks in node.out_hooks.items():
            if out_cots[i] is not None:
                g = out_cots[i]
                for hook in hooks:
                    out = hook(_wrap(g))
                    if out is not None:
                        g = out._value if isinstance(out, Tensor) else out
                out_cots[i] = g
        if inputs is not None:
            for i in range(node.n_outputs):
                key = (id(node), i)
                if key in want_by_nodeidx and out_cots[i] is not None:
                    for slot in want_by_nodeidx[key]:
                        results[slot] = (out_cots[i] if results[slot] is None
                                         else results[slot] + out_cots[i])
        in_grads = node.run_vjp(out_cots, create_graph=create_graph)
        for edge, g in zip(node.edges, in_grads):
            if edge[0] == "leaf":
                if g is not None:
                    _emit_leaf(edge[1], g)
                continue
            _, prev, idx = edge
            if g is not None:
                cot.add(prev, idx, g)
            # the in-degree decrement must happen even for a None grad (e.g. a
            # PyLayer backward returning None), or the upstream node never
            # becomes ready and its other consumers' grads are dropped
            indeg[id(prev)] -= 1
            if indeg[id(prev)] == 0:
                ready.append(prev)
        if not retain_graph:
            node.release()

    # Nodes never reached keep their buffers; with retain_graph=False the whole
    # reachable graph is now released, matching reference semantics.
    if inputs is not None:
        return results
    return None


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad parity: partial grads w.r.t. `inputs` without touching .grad."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    vals = run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                        create_graph=create_graph, inputs=inputs,
                        accumulate=False)
    results = []
    for t, v in zip(inputs, vals):
        if v is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph. Set allow_unused=True if this "
                    "is intended.")
            results.append(None)
        elif isinstance(v, Tensor):
            results.append(v)
        else:
            results.append(Tensor(v, stop_gradient=not create_graph))
    return results
