"""paddle_tpu.sparse.nn: layers over sparse tensors.

Role parity: `paddle.sparse.nn` (`python/paddle/sparse/nn/`) — activation
layers, sparse conv3d (point-cloud workloads), batch norm, pooling. The
reference's submanifold conv uses gather/scatter rulebooks on GPU
(`paddle/phi/kernels/sparse/gpu/conv_kernel.cu`); here Conv3D densifies the
local neighborhood — a correct baseline (XLA fuses the gather chain) with
the rulebook-free layout TPUs prefer; swap in a Pallas rulebook kernel if
point-cloud perf becomes a target.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu

        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from . import relu6

        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from . import leaky_relu

        return leaky_relu(x, self.negative_slope)


class Sigmoid(Layer):
    def forward(self, x):
        from . import sigmoid

        return sigmoid(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from . import softmax

        return softmax(x, self.axis)


class BatchNorm(Layer):
    """Batch norm over sparse values (per-channel on the last dense dim),
    parity: paddle.sparse.nn.BatchNorm on NDHWC sparse tensors."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC"):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        from ..nn.initializer import Constant

        self.weight = self.create_parameter(
            [num_features], default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features,
                                                      np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features,
                                                         np.float32)))

    def forward(self, x):
        from . import SparseCooTensor

        vals = x.values()
        if self.training:
            def stats(v):
                mean = jnp.mean(v, axis=0)
                var = jnp.var(v, axis=0)
                return mean, var

            mean_t, var_t = apply("sparse_bn_stats", stats, vals)
            m, v_ = mean_t._value, var_t._value
            self._mean._value = (self.momentum * self._mean._value
                                 + (1 - self.momentum) * m)
            self._variance._value = (self.momentum * self._variance._value
                                     + (1 - self.momentum) * v_)
        else:
            mean_t, var_t = Tensor(self._mean._value), Tensor(
                self._variance._value)

        def norm(v, m, var, w, b):
            return (v - m) * jax.lax.rsqrt(var + self.epsilon) * w + b

        out_vals = apply("sparse_bn", norm, vals, mean_t, var_t,
                         self.weight, self.bias)
        return SparseCooTensor(x.indices_arr, out_vals, x.dense_shape,
                               x.coalesced)


SyncBatchNorm = BatchNorm


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 data_format="NDHWC"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * 3
        self.kernel_size = list(ks)
        self.stride = stride if isinstance(stride, (list, tuple)) \
            else [stride] * 3
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 3
        self.dilation = dilation if isinstance(dilation, (list, tuple)) \
            else [dilation] * 3
        self.groups = groups
        self.subm = subm
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.weight = self.create_parameter(
            list(ks) + [in_channels // groups, out_channels])
        self.bias = self.create_parameter([out_channels], is_bias=True)

    def forward(self, x):
        """Densify → lax conv → resparsify (submanifold keeps x's indices).

        Baseline implementation; see module docstring.
        """
        from . import SparseCooTensor, mask_as, to_sparse_coo_from_dense

        dense = x.to_dense()  # [N, D, H, W, C]

        def conv(d, w, b):
            out = jax.lax.conv_general_dilated(
                d, w,
                window_strides=self.stride,
                padding=[(p, p) for p in self.padding],
                rhs_dilation=self.dilation,
                feature_group_count=self.groups,
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
            return out + b

        out_dense = apply("sparse_conv3d", conv, dense, self.weight,
                          self.bias)
        if self.subm:
            return mask_as(out_dense, x)
        return to_sparse_coo_from_dense(out_dense, sparse_dim=4)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, subm=False,
                         **kw)


class SubmConv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, subm=True,
                         **kw)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * 3
        self.kernel_size = list(ks)
        if isinstance(stride, (list, tuple)):
            self.stride = list(stride)
        elif stride:
            self.stride = [stride] * 3
        else:
            self.stride = list(self.kernel_size)
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 3

    def forward(self, x):
        from . import to_sparse_coo_from_dense

        dense = x.to_dense()

        def pool(d):
            return jax.lax.reduce_window(
                d, -jnp.inf, jax.lax.max,
                (1, *self.kernel_size, 1), (1, *self.stride, 1),
                [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)])

        out = apply("sparse_maxpool3d", pool, dense)
        return to_sparse_coo_from_dense(out, sparse_dim=4)
