"""paddle_tpu.sparse: COO/CSR sparse tensors + ops + nn.

Role parity: `paddle.sparse` (`python/paddle/sparse/`, SURVEY §2.6; kernels
`paddle/phi/kernels/sparse/`, tensor types `paddle/phi/core/sparse_coo_tensor.h`,
`sparse_csr_tensor.h`).

TPU-first design: a sparse tensor is (index arrays, values Tensor, dense
shape). The values Tensor carries autograd — every sparse op routes its
value math through the regular dispatch gate, so grads flow with no extra
machinery. Compute patterns XLA likes: matmul/sddmm as gather +
`segment_sum` (static-nnz, MXU-friendly per-row accumulation) rather than
scalar loops; nnz is static per tensor, so everything jits.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO: indices [sparse_dim, nnz] int64, values [nnz, *dense_dims]."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices_arr = jnp.asarray(_val(indices), jnp.int32)
        self.values_t = values if isinstance(values, Tensor) else Tensor(values)
        self.dense_shape = tuple(int(s) for s in shape)
        self.coalesced = coalesced

    # --- paddle Tensor-surface parity ---
    @property
    def shape(self):
        return list(self.dense_shape)

    @property
    def dtype(self):
        return self.values_t.dtype

    @property
    def ndim(self):
        return len(self.dense_shape)

    @property
    def nnz(self):
        return int(self.indices_arr.shape[1])

    @property
    def stop_gradient(self):
        return self.values_t.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values_t.stop_gradient = v

    @property
    def grad(self):
        return self.values_t.grad

    def values(self):
        return self.values_t

    def indices(self):
        return Tensor(self.indices_arr)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def backward(self, *a, **kw):
        return self.values_t.backward(*a, **kw)

    def to_dense(self):
        idx = self.indices_arr
        shape = self.dense_shape
        sparse_dim = idx.shape[0]

        def f(v):
            out = jnp.zeros(shape, v.dtype)
            return out.at[tuple(idx[d] for d in range(sparse_dim))].add(v)

        return apply("sparse_coo_to_dense", f, self.values_t)

    def to_sparse_csr(self):
        coo = self.coalesce()
        m = coo.dense_shape[0]
        rows = coo.indices_arr[0]
        crows = jnp.zeros(m + 1, jnp.int32).at[rows + 1].add(1)
        crows = jnp.cumsum(crows)
        return SparseCsrTensor(crows, coo.indices_arr[1], coo.values_t,
                               coo.dense_shape)

    def coalesce(self):
        if self.coalesced:
            return self
        idx = np.asarray(self.indices_arr)
        flat = np.ravel_multi_index(
            tuple(idx), self.dense_shape[:idx.shape[0]])
        order = np.argsort(flat, kind="stable")
        sorted_flat = flat[order]
        uniq, first = np.unique(sorted_flat, return_index=True)
        seg = np.searchsorted(uniq, sorted_flat)
        new_idx = idx[:, order][:, first]
        n_out = len(uniq)
        perm = jnp.asarray(order)
        seg_j = jnp.asarray(seg)

        def f(v):
            return jax.ops.segment_sum(v[perm], seg_j, num_segments=n_out)

        new_vals = apply("sparse_coalesce", f, self.values_t)
        return SparseCooTensor(new_idx, new_vals, self.dense_shape,
                               coalesced=True)

    def numpy(self):
        return self.to_dense().numpy()

    def astype(self, dtype):
        return SparseCooTensor(self.indices_arr, self.values_t.astype(dtype),
                               self.dense_shape, self.coalesced)

    cast = astype

    def detach(self):
        return SparseCooTensor(self.indices_arr, self.values_t.detach(),
                               self.dense_shape, self.coalesced)

    def transpose(self, perm):
        nd = len(self.dense_shape)
        sd = self.indices_arr.shape[0]
        if any(p >= sd for p in perm[:sd]) or any(p < sd for p in perm[sd:]):
            raise NotImplementedError(
                "transpose mixing sparse and dense dims")
        new_idx = jnp.stack([self.indices_arr[p] for p in perm[:sd]])
        new_shape = tuple(self.dense_shape[p] for p in perm)
        vals = self.values_t
        if sd < nd:
            # permute the trailing dense dims of values ([nnz, *dense_dims])
            val_perm = [0] + [1 + (perm[i] - sd) for i in range(sd, nd)]
            if val_perm != list(range(nd - sd + 1)):
                from .. import ops

                vals = ops.transpose(vals, val_perm)
        return SparseCooTensor(new_idx, vals, new_shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR: crows [m+1], cols [nnz], values [nnz] (2D; batched 3D via
    leading batch handled by callers)."""

    def __init__(self, crows, cols, values, shape):
        self.crows_arr = jnp.asarray(_val(crows), jnp.int32)
        self.cols_arr = jnp.asarray(_val(cols), jnp.int32)
        self.values_t = values if isinstance(values, Tensor) else Tensor(values)
        self.dense_shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self.dense_shape)

    @property
    def dtype(self):
        return self.values_t.dtype

    @property
    def ndim(self):
        return len(self.dense_shape)

    @property
    def nnz(self):
        return int(self.cols_arr.shape[0])

    @property
    def stop_gradient(self):
        return self.values_t.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values_t.stop_gradient = v

    @property
    def grad(self):
        return self.values_t.grad

    def values(self):
        return self.values_t

    def crows(self):
        return Tensor(self.crows_arr)

    def cols(self):
        return Tensor(self.cols_arr)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def backward(self, *a, **kw):
        return self.values_t.backward(*a, **kw)

    def _rows(self):
        m = self.dense_shape[0]
        counts = jnp.diff(self.crows_arr)
        return jnp.repeat(jnp.arange(m, dtype=jnp.int32), counts,
                          total_repeat_length=self.nnz)

    def to_sparse_coo(self, sparse_dim=2):
        idx = jnp.stack([self._rows(), self.cols_arr])
        return SparseCooTensor(idx, self.values_t, self.dense_shape,
                               coalesced=True)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return self.to_dense().numpy()

    def detach(self):
        return SparseCsrTensor(self.crows_arr, self.cols_arr,
                               self.values_t.detach(), self.dense_shape)

    def astype(self, dtype):
        return SparseCsrTensor(self.crows_arr, self.cols_arr,
                               self.values_t.astype(dtype), self.dense_shape)

    cast = astype

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# --- creation ---------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(_val(indices), jnp.int32)
    vals = values if isinstance(values, Tensor) else Tensor(values, dtype=dtype)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        if idx.shape[1] == 0:
            raise ValueError(
                "shape is required for an empty (nnz=0) sparse tensor")
        sparse_shape = [int(i) + 1 for i in np.asarray(idx.max(axis=1))]
        shape = sparse_shape + list(vals.shape[1:])
    vals.stop_gradient = stop_gradient
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = values if isinstance(values, Tensor) else Tensor(values, dtype=dtype)
    if dtype is not None:
        vals = vals.astype(dtype)
    vals.stop_gradient = stop_gradient
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# --- unary value-wise ops ----------------------------------------------------

def _unary(name, f):
    def g(x, name_arg=None):
        out_vals = apply(f"sparse_{name}", f, x.values())
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices_arr, out_vals, x.dense_shape,
                                   x.coalesced)
        return SparseCsrTensor(x.crows_arr, x.cols_arr, out_vals,
                               x.dense_shape)

    g.__name__ = name
    return g


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)
relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", lambda v: jnp.clip(v, 0, 6))
sigmoid = _unary("sigmoid", jax.nn.sigmoid)


def leaky_relu(x, negative_slope=0.01):
    return _unary("leaky_relu",
                  lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def pow(x, factor):
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    out = x
    if value_dtype is not None:
        out = out.astype(value_dtype)
    return out


def scale(x, scale_val, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return _unary("scale", lambda v: v * scale_val + bias)(x)
    return _unary("scale", lambda v: (v + bias) * scale_val)(x)


# --- binary -----------------------------------------------------------------

def _ewise_coo(name, f, x, y):
    """Elementwise op over two COO tensors via union of index sets."""
    xc, yc = x.coalesce(), y.coalesce()
    xi = np.asarray(xc.indices_arr)
    yi = np.asarray(yc.indices_arr)
    sd = xi.shape[0]
    shape = x.dense_shape[:sd]
    xf = np.ravel_multi_index(tuple(xi), shape)
    yf = np.ravel_multi_index(tuple(yi), shape)
    union = np.union1d(xf, yf)
    xpos = jnp.asarray(np.searchsorted(union, xf))
    ypos = jnp.asarray(np.searchsorted(union, yf))
    n = len(union)
    new_idx = np.stack(np.unravel_index(union, shape)).astype(np.int32)
    val_shape = (n,) + tuple(xc.values_t.shape[1:])

    def g(xv, yv):
        dx = jnp.zeros(val_shape, xv.dtype).at[xpos].set(xv)
        dy = jnp.zeros(val_shape, yv.dtype).at[ypos].set(yv)
        return f(dx, dy)

    out_vals = apply(f"sparse_{name}", g, xc.values_t, yc.values_t)
    return SparseCooTensor(new_idx, out_vals, x.dense_shape, coalesced=True)


def _binary(name, f):
    def g(x, y, name_arg=None):
        if isinstance(x, SparseCsrTensor):
            x = x.to_sparse_coo()
        if isinstance(y, SparseCsrTensor):
            y = y.to_sparse_coo()
        if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
            if list(x.dense_shape) != list(y.dense_shape):
                raise ValueError("sparse binary op needs same shapes")
            return _ewise_coo(name, f, x, y)
        # sparse op dense → dense
        xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
        yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
        return apply(f"sparse_{name}_dense", f, xd, yd)

    g.__name__ = name
    return g


add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)


# --- matmul family ----------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense → dense (spmm). COO path: gather + segment_sum."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError("matmul: x must be sparse")
    if x.ndim != 2:
        raise NotImplementedError("sparse matmul supports 2D for now")
    rows = x.indices_arr[0]
    cols = x.indices_arr[1]
    m = x.dense_shape[0]

    def f(v, d):
        contrib = v[:, None] * d[cols]         # [nnz, n]
        return jax.ops.segment_sum(contrib, rows, num_segments=m)

    yt = y if isinstance(y, Tensor) else Tensor(y)
    return apply("sparse_matmul", f, x.values_t, yt)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at mask's sparsity pattern (SDDMM)."""
    if isinstance(mask, SparseCsrTensor):
        coo_mask = mask.to_sparse_coo()
    else:
        coo_mask = mask
    rows, cols = coo_mask.indices_arr[0], coo_mask.indices_arr[1]

    def f(xa, ya):
        return jnp.sum(xa[rows] * ya.T[cols], axis=-1)

    xt = x if isinstance(x, Tensor) else Tensor(x)
    yt = y if isinstance(y, Tensor) else Tensor(y)
    out_vals = apply("sparse_masked_matmul", f, xt, yt)
    if isinstance(mask, SparseCsrTensor):
        return SparseCsrTensor(mask.crows_arr, mask.cols_arr, out_vals,
                               mask.dense_shape)
    return SparseCooTensor(coo_mask.indices_arr, out_vals,
                           coo_mask.dense_shape, coalesced=True)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("sparse_addmm", lambda i, mm: beta * i + alpha * mm,
                 input if isinstance(input, Tensor) else Tensor(input),
                 matmul(x, y))


def mv(x, vec, name=None):
    out = matmul(x, (vec if isinstance(vec, Tensor)
                     else Tensor(vec)).reshape([-1, 1]))
    from .. import ops

    return ops.reshape(out, [-1])


# --- reductions / manipulation ----------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    if axis is None:
        out = apply("sparse_sum_all", lambda v: jnp.sum(v), x.values())
        return out.astype(dtype) if dtype is not None else out
    dense = apply("sparse_sum_axis",
                  lambda d: jnp.sum(d, axis=axis, keepdims=keepdim),
                  x.to_dense())
    if dtype is not None:
        dense = dense.astype(dtype)
    # paddle.sparse.sum stays sparse
    out = to_sparse_coo_from_dense(dense)
    if isinstance(x, SparseCsrTensor) and out.ndim == 2:
        return out.to_sparse_csr()
    return out


def transpose(x, perm, name=None):
    return x.transpose(perm)


def reshape(x, shape, name=None):
    dense = x.to_dense()
    from .. import ops

    return to_sparse_coo_from_dense(ops.reshape(dense, shape),
                                    sparse_dim=len(shape))


def coalesce(x, name=None):
    return x.coalesce()


def mask_as(x, mask, name=None):
    """Sample dense x at mask's sparsity pattern (trailing dense dims come
    from x: mask only fixes the sparse-index pattern)."""
    coo = mask if isinstance(mask, SparseCooTensor) else mask.to_sparse_coo()
    idx = coo.indices_arr
    sd = idx.shape[0]
    xt = x if isinstance(x, Tensor) else Tensor(x)
    out_vals = apply("sparse_mask_as",
                     lambda d: d[tuple(idx[i] for i in range(sd))], xt)
    out_shape = tuple(coo.dense_shape[:sd]) + tuple(xt.shape[sd:])
    if isinstance(mask, SparseCsrTensor):
        return SparseCsrTensor(mask.crows_arr, mask.cols_arr, out_vals,
                               out_shape)
    return SparseCooTensor(idx, out_vals, out_shape, coo.coalesced)


def to_sparse_coo_from_dense(dense, sparse_dim=None):
    arr = np.asarray(dense._value if isinstance(dense, Tensor) else dense)
    sparse_dim = sparse_dim or arr.ndim
    reduce_axes = tuple(range(sparse_dim, arr.ndim))
    nz_mask = (arr != 0)
    if reduce_axes:
        nz_mask = nz_mask.any(axis=reduce_axes)
    idx = np.stack(np.nonzero(nz_mask)).astype(np.int32)
    pos = tuple(idx)
    dt = dense if isinstance(dense, Tensor) else Tensor(dense)

    def f(d):
        return d[pos]

    vals = apply("dense_to_sparse_coo", f, dt)
    return SparseCooTensor(idx, vals, arr.shape, coalesced=True)


# softmax over CSR rows (sparse attention building block)
def softmax(x, axis=-1, name=None):
    if axis not in (-1, x.ndim - 1):
        raise NotImplementedError(
            "sparse softmax supports the last axis only (per-row)")
    if isinstance(x, SparseCooTensor):
        return _coo_softmax(x)
    rows = x._rows()
    m = x.dense_shape[0]

    def f(v):
        row_max = jax.ops.segment_max(v, rows, num_segments=m)
        e = jnp.exp(v - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=m)
        return e / denom[rows]

    out_vals = apply("sparse_softmax", f, x.values_t)
    return SparseCsrTensor(x.crows_arr, x.cols_arr, out_vals, x.dense_shape)


def _coo_softmax(x):
    csr = x.to_sparse_csr()
    return softmax(csr).to_sparse_coo()


from . import nn  # noqa: E402,F401

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "sin", "tan", "asin", "atan",
    "sinh", "tanh", "asinh", "atanh", "sqrt", "square", "log1p", "abs",
    "expm1", "neg", "rad2deg", "deg2rad", "relu", "relu6", "sigmoid",
    "leaky_relu", "pow", "cast", "scale", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "addmm", "mv", "sum", "transpose",
    "reshape", "coalesce", "mask_as", "softmax", "nn",
]


def isnan(x, name=None):
    """Elementwise NaN test on the stored values (paddle.sparse.isnan)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor as _T

    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        vals = x.values()
        out = jnp.isnan(vals._value if isinstance(vals, _T) else vals)
        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(x.indices(), _T(out), x.shape)
        return sparse_csr_tensor(x.crows(), x.cols(), _T(out), x.shape)
    return _T(jnp.isnan(_val(x)))


def slice(x, axes, starts, ends, name=None):
    """Dense-region slice of a sparse tensor (paddle.sparse.slice):
    computed on the dense form, returned sparse-COO."""
    import numpy as np_

    from .. import ops as _ops
    from ..core.tensor import Tensor as _T

    dense = x.to_dense() if hasattr(x, "to_dense") else x
    out = _ops.slice(dense, axes, starts, ends)
    arr = np_.asarray(out._value)
    nz = np_.nonzero(arr)
    idx = np_.stack(nz)
    return sparse_coo_tensor(_T(idx.astype(np_.int64)),
                             _T(arr[nz]), list(arr.shape))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA of a sparse matrix (paddle.sparse.pca_lowrank):
    densify (the factors are dense anyway) and reuse the dense routine."""
    import paddle_tpu as P

    dense = x.to_dense() if hasattr(x, "to_dense") else x
    return P.pca_lowrank(dense, q=q, center=center, niter=niter)
