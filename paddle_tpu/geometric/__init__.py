"""paddle_tpu.geometric: graph-learning message passing + sampling.

Role parity: `paddle.geometric` (`python/paddle/geometric/`, SURVEY §2.8) —
`send_u_recv`/`send_ue_recv`/`send_uv` message passing, segment reductions,
neighbor sampling, and reindexing.

TPU-first: message passing is gather + `jax.ops.segment_*` with a static
num_segments (out_size) — the layout XLA vectorizes; no dynamic-shape
scatter kernels as in the reference's CUDA `graph_send_recv` ops. Sampling
and reindex are host-side (numpy) as in the reference's CPU path: they
produce the static shapes the device graph then consumes.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "reindex_heter_graph",
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "sample_neighbors", "reindex_graph", "weighted_sample_neighbors",
]


def _ival(x):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return v.astype(jnp.int32)


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed below
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment_reduce(data, seg, n, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(data, seg, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  seg, num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (data.ndim - 1))
    out = _REDUCERS[pool](data, seg, num_segments=n)
    if pool in ("max", "min"):
        # empty segments come back ±inf; zero them as the reference does
        out = jnp.where(jnp.isfinite(out), out, jnp.zeros((), out.dtype))
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and segment-reduce onto dst (paddle.geometric.send_u_recv,
    reference kernel `paddle/phi/kernels/gpu/graph_send_recv_kernel.cu`)."""
    src = _ival(src_index)
    dst = _ival(dst_index)
    n = int(out_size) if out_size is not None else int(x.shape[0])
    pool = reduce_op.lower()

    def f(xv):
        return _segment_reduce(xv[src], dst, n, pool)

    return apply("send_u_recv", f, x)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Messages combine node features x[src] with edge features y."""
    src = _ival(src_index)
    dst = _ival(dst_index)
    n = int(out_size) if out_size is not None else int(x.shape[0])
    pool = reduce_op.lower()
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.true_divide}[message_op.lower()]

    def f(xv, yv):
        return _segment_reduce(combine(xv[src], yv), dst, n, pool)

    return apply("send_ue_recv", f, x, y)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge messages combining x[src] with y[dst] (no reduce)."""
    src = _ival(src_index)
    dst = _ival(dst_index)
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.true_divide}[message_op.lower()]

    def f(xv, yv):
        return combine(xv[src], yv[dst])

    return apply("send_uv", f, x, y)


def _segment_api(pool):
    def g(data, segment_ids, name=None):
        seg = _ival(segment_ids)
        n = int(np.asarray(seg).max()) + 1 if seg.shape[0] else 0

        def f(d):
            return _segment_reduce(d, seg, n, pool)

        return apply(f"segment_{pool}", f, data)

    g.__name__ = f"segment_{pool}"
    return g


segment_sum = _segment_api("sum")
segment_mean = _segment_api("mean")
segment_max = _segment_api("max")
segment_min = _segment_api("min")


# --- host-side sampling/reindex (CPU path parity) ---------------------------

def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling on a CSC graph (host-side numpy; parity:
    `paddle.geometric.sample_neighbors`)."""
    rowv = np.asarray(row._value if isinstance(row, Tensor) else row)
    colp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(
        input_nodes._value if isinstance(input_nodes, Tensor)
        else input_nodes)
    out_nb, out_cnt, out_eids = [], [], []
    rng = np.random
    for nd in nodes.ravel():
        lo, hi = int(colp[nd]), int(colp[nd + 1])
        nbrs = rowv[lo:hi]
        ids = np.arange(lo, hi)
        if sample_size != -1 and len(nbrs) > sample_size:
            pick = rng.choice(len(nbrs), size=sample_size, replace=False)
            nbrs = nbrs[pick]
            ids = ids[pick]
        out_nb.append(nbrs)
        out_eids.append(ids)
        out_cnt.append(len(nbrs))
    nbr = Tensor(np.concatenate(out_nb) if out_nb
                 else np.zeros(0, rowv.dtype))
    cnt = Tensor(np.asarray(out_cnt, np.int32))
    if return_eids:
        ids = (np.concatenate(out_eids) if out_eids
               else np.zeros(0, np.int64))
        # caller-provided eids map CSR slots to real edge ids (reference
        # gathers returned ids from it); without it, slots ARE the ids
        if eids is not None:
            ev = np.asarray(eids._value if isinstance(eids, Tensor) else eids)
            ids = ev[ids]
        return nbr, cnt, Tensor(ids)
    return nbr, cnt


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    rowv = np.asarray(row._value if isinstance(row, Tensor) else row)
    colp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr)
    w = np.asarray(edge_weight._value if isinstance(edge_weight, Tensor)
                   else edge_weight)
    nodes = np.asarray(
        input_nodes._value if isinstance(input_nodes, Tensor)
        else input_nodes)
    out_nb, out_cnt, out_eids = [], [], []
    for nd in nodes.ravel():
        lo, hi = int(colp[nd]), int(colp[nd + 1])
        nbrs = rowv[lo:hi]
        ww = w[lo:hi]
        ids = np.arange(lo, hi)
        if sample_size != -1 and len(nbrs) > sample_size:
            p = ww / ww.sum()
            pick = np.random.choice(len(nbrs), size=sample_size,
                                    replace=False, p=p)
            nbrs = nbrs[pick]
            ids = ids[pick]
        out_nb.append(nbrs)
        out_eids.append(ids)
        out_cnt.append(len(nbrs))
    nbr = Tensor(np.concatenate(out_nb) if out_nb
                 else np.zeros(0, rowv.dtype))
    cnt = Tensor(np.asarray(out_cnt, np.int32))
    if return_eids:
        ids = (np.concatenate(out_eids) if out_eids
               else np.zeros(0, np.int64))
        if eids is not None:
            ev = np.asarray(eids._value if isinstance(eids, Tensor) else eids)
            ids = ev[ids]
        return nbr, cnt, Tensor(ids)
    return nbr, cnt


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (parity:
    `paddle.geometric.reindex_graph`)."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x).ravel()
    nb = np.asarray(
        neighbors._value if isinstance(neighbors, Tensor)
        else neighbors).ravel()
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count)
    mapping = {}
    for nd in xv:
        mapping.setdefault(int(nd), len(mapping))
    for nd in nb:
        mapping.setdefault(int(nd), len(mapping))
    reindex_nb = np.asarray([mapping[int(v)] for v in nb], np.int64)
    # reconstruct dst from counts: node i repeated count[i] times
    dst = np.repeat(np.arange(len(xv)), cnt)
    nodes = np.asarray(sorted(mapping, key=mapping.get), np.int64)
    return Tensor(reindex_nb), Tensor(dst), Tensor(nodes)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant of reindex_graph (paddle.geometric.
    reindex_heter_graph): per-relation neighbor/count lists share one
    node-id remapping."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x).ravel()
    nbs = [np.asarray(n._value if isinstance(n, Tensor) else n).ravel()
           for n in neighbors]
    cnts = [np.asarray(c._value if isinstance(c, Tensor) else c)
            for c in count]
    mapping = {}
    for nd in xv:
        mapping.setdefault(int(nd), len(mapping))
    outs = []
    for nb in nbs:
        loc = np.empty_like(nb)
        for i, nd in enumerate(nb):
            loc[i] = mapping.setdefault(int(nd), len(mapping))
        outs.append(loc)
    nodes = np.empty(len(mapping), dtype=xv.dtype)
    for nd, i in mapping.items():
        nodes[i] = nd
    reindex_src = Tensor(jnp.asarray(np.concatenate(outs)
                                     if outs else np.empty(0, xv.dtype)))
    total = int(sum(int(c.sum()) for c in cnts))
    dst = np.empty(total, dtype=xv.dtype)
    off = 0
    for cnt in cnts:
        for i, c in enumerate(np.ravel(cnt)):
            dst[off:off + int(c)] = i
            off += int(c)
    return reindex_src, Tensor(jnp.asarray(dst)), Tensor(jnp.asarray(nodes))
