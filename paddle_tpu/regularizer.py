"""paddle.regularizer parity (`/root/reference/python/paddle/regularizer.py`):
L1/L2 weight-decay descriptors consumed by the optimizer layer (which folds
them into the jit-compiled update step rather than adding graph ops)."""
from .optimizer.optimizer import L1Decay, L2Decay  # noqa: F401


class WeightDecayRegularizer:
    """Base marker class (reference `python/paddle/regularizer.py:23`)."""


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]
