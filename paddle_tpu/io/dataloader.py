"""DataLoader (paddle.io.DataLoader parity: `python/paddle/io/reader.py:216`,
iterators `dataloader_iter.py:150,358`).

TPU-first: worker threads (not processes) prefetch + collate into numpy;
device transfer is a single `jax.device_put` per batch riding XLA's async
dispatch, playing the role of the reference's pin-memory thread + shared-mem
tensor transport. A C++ shared-memory ring (multiprocess workers) is the
planned upgrade for heavy CPU-bound pipelines.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def _fetch_retry():
    """Retry policy for `dataloader.batch`: a flaky storage read (or an
    injected fault) re-fetches the same indices instead of killing the
    epoch.  PADDLE_TPU_DATALOADER_RETRIES tunes attempts (default 2)."""
    from ..resilience.retry import env_policy

    return env_policy(
        "dataloader", "PADDLE_TPU_DATALOADER_RETRIES", 2,
        base_delay=0.01, max_delay=0.2,
        # deterministic dataset bugs (bad index math, type errors in
        # collate) fail the same way twice — don't re-fetch.  ValueError
        # is DELIBERATELY retryable here: truncated/corrupt reads often
        # surface as decode ValueErrors and deserve one re-fetch.
        give_up_on=(TypeError, KeyError, AttributeError, IndexError))


def _fire_batch_fault(n):
    from ..resilience import faults as _faults

    _faults.fire("dataloader.batch", n=int(n))


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._value) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = max(2, prefetch_factor)
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
            self.batch_size = None
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no deterministic length")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        def _once():
            _fire_batch_fault(len(indices))
            samples = [self.dataset[i] for i in indices]
            return self.collate_fn(samples)

        return _fetch_retry().call(_once)

    def _iter_single(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    # fault point only (no retry: an iterable source
                    # cannot be re-asked for the same items)
                    _fire_batch_fault(len(batch))
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                _fire_batch_fault(len(batch))
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_workers(self):
        """Thread-pool prefetch: index batches fan out to workers; results are
        re-ordered to preserve determinism."""
        assert not self._iterable_mode, \
            "num_workers>0 with IterableDataset not supported yet"
        index_q = queue.Queue()
        out_q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        batches = list(self.batch_sampler)
        for i, b in enumerate(batches):
            index_q.put((i, b))
        stop = object()

        def worker(wid):
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while True:
                try:
                    i, idxs = index_q.get_nowait()
                except queue.Empty:
                    out_q.put(stop)
                    return
                try:
                    out_q.put((i, self._fetch(idxs)))
                except Exception as e:  # surface worker errors
                    out_q.put((i, e))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        pending = {}
        next_idx = 0
        finished_workers = 0
        total = len(batches)
        while next_idx < total:
            item = out_q.get()
            if item is stop:
                finished_workers += 1
                if finished_workers == len(threads) and next_idx < total \
                        and not pending:
                    break
                continue
            i, data = item
            if isinstance(data, Exception):
                raise data
            pending[i] = data
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1

    def __iter__(self):
        if self.num_workers > 0 and self.use_shared_memory and \
                not self._iterable_mode and self.batch_sampler is not None:
            # process workers + native shm ring (GIL-free transport)
            from .shm_queue import run_process_workers

            try:
                return run_process_workers(
                    self.dataset, list(self.batch_sampler), self.collate_fn,
                    self.num_workers, worker_init_fn=self.worker_init_fn)
            except (OSError, ValueError):
                # no native toolchain / non-module-level collate_fn:
                # fall through to thread workers
                pass
        if self.num_workers > 0:
            return self._iter_workers()
        return self._iter_single()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker process: its (id, num_workers, dataset);
    None in the main process (paddle.io.get_worker_info)."""
    return _worker_info
