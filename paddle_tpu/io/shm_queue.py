"""Python wrapper over the native shared-memory ring + multiprocess
DataLoader workers (reference role: multiprocess dataloader_iter with
mmap-allocator tensor transport, `python/paddle/io/dataloader/
dataloader_iter.py:358`)."""
from __future__ import annotations

import os
import pickle
import uuid

from .. import native
from ..observability import metrics as _metrics


class ShmQueue:
    """Fixed-slot shared-memory message queue usable across fork()."""

    def __init__(self, n_slots=8, slot_size=32 << 20, name=None, create=True):
        self.lib = native.load()
        self.name = (name or f"/ptpu_{os.getpid()}_{uuid.uuid4().hex[:8]}")
        self._owner = create
        if create:
            self.ring = self.lib.shm_ring_create(
                self.name.encode(), n_slots, slot_size)
        else:
            self.ring = self.lib.shm_ring_attach(self.name.encode())
        if not self.ring:
            raise OSError(f"shm ring setup failed for {self.name}")
        self.slot_size = int(self.lib.shm_ring_slot_size(self.ring))
        self._buf = None  # lazy reusable pop buffer (hot path: no per-pop
                          # slot_size alloc+memset)

    def attach(self):
        return ShmQueue(name=self.name, create=False)

    def put(self, obj, timeout=60.0):
        payload = pickle.dumps(obj, protocol=4)
        if len(payload) > self.slot_size:
            raise ValueError(
                f"message of {len(payload)}B exceeds slot size "
                f"{self.slot_size}B; raise slot_size")
        rc = self.lib.shm_ring_push(self.ring, payload, len(payload),
                                    float(timeout))
        if rc == -1:
            raise TimeoutError("shm push timeout")
        if rc == -2:
            raise BrokenPipeError("shm ring closed")

    def get(self, timeout=60.0):
        import ctypes

        if self._buf is None:
            self._buf = ctypes.create_string_buffer(self.slot_size)
        n = self.lib.shm_ring_pop(self.ring, self._buf, self.slot_size,
                                  float(timeout))
        if n == -1:
            raise TimeoutError("shm pop timeout")
        if n == -2:
            raise EOFError("shm ring closed and drained")
        return pickle.loads(ctypes.string_at(self._buf, n))

    def qsize(self):
        return int(self.lib.shm_ring_count(self.ring))

    def close(self):
        self.lib.shm_ring_close(self.ring)

    def __del__(self):
        try:
            if getattr(self, "ring", None):
                self.lib.shm_ring_detach(self.ring)
                if self._owner:
                    self.lib.shm_ring_unlink(self.name.encode())
        except Exception:
            # module-top import on purpose: importing inside a __del__
            # handler can itself raise at interpreter shutdown
            _metrics.inc("io.shm_del_errors")


def _worker_main(dataset, batches, indices, collate_path, queue_name,
                 worker_init_fn, wid):
    """Spawned worker entry: fetch+collate assigned batches into the ring.
    Exceptions are shipped back through the ring (index -1) so the parent
    surfaces the real dataset error instead of timing out."""
    import importlib
    import traceback

    q = ShmQueue(name=queue_name, create=False)
    try:
        mod_name, fn_name = collate_path
        collate_fn = getattr(importlib.import_module(mod_name), fn_name)
        if worker_init_fn is not None:
            worker_init_fn(wid)
        for i in indices:
            samples = [dataset[j] for j in batches[i]]
            payload = _to_numpy_tree(collate_fn(samples))
            q.put((i, payload))
    except Exception:
        q.put((-1, f"DataLoader worker {wid} died:\n"
                   f"{traceback.format_exc()}"))
        raise


def run_process_workers(dataset, batches, collate_fn, num_workers,
                        queue_slots=8, slot_size=32 << 20,
                        worker_init_fn=None):
    """Spawned worker processes fetch+collate batches into the shm ring;
    yields batches in order. True multiprocess loading: the transport is the
    native ring (no pipe/pickle through the parent's GIL); spawn (not fork)
    keeps the multithreaded jax runtime safe."""
    import multiprocessing as mp

    # validation + native load + spawn happen eagerly at call time (NOT
    # inside the generator) so DataLoader.__iter__ can catch OSError /
    # ValueError and fall back to thread workers
    collate_path = (collate_fn.__module__, collate_fn.__qualname__)
    if "." in collate_path[1] or "<" in collate_path[1]:
        raise ValueError(
            "collate_fn must be a module-level function for process workers")

    q = ShmQueue(n_slots=queue_slots, slot_size=slot_size)
    n = len(batches)
    ctx = mp.get_context("spawn")
    procs = []
    # workers are CPU/numpy-only: strip accelerator-claiming env so spawned
    # interpreters never register/initialize a TPU client (which can block
    # on the device tunnel at interpreter start)
    strip = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")
    saved = {k: os.environ.pop(k) for k in strip if k in os.environ}
    saved["JAX_PLATFORMS"] = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        for w in range(num_workers):
            idxs = list(range(w, n, num_workers))
            p = ctx.Process(target=_worker_main,
                            args=(dataset, batches, idxs, collate_path,
                                  q.name, worker_init_fn, w), daemon=True)
            p.start()
            procs.append(p)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    return _consume(q, procs, n)


def _consume(q, procs, n, deadline_s=300.0):
    import time

    pending = {}
    next_idx = 0
    received = 0
    deadline = time.monotonic() + deadline_s
    try:
        while received < n:
            try:
                # short poll so worker death is noticed promptly; the
                # deadline bounds total wait even if workers stay alive
                i, payload = q.get(timeout=5.0)
                deadline = time.monotonic() + deadline_s
            except TimeoutError:
                crashed = [p for p in procs
                           if not p.is_alive() and p.exitcode not in (0, None)]
                if crashed and q.qsize() == 0:
                    raise RuntimeError(
                        f"DataLoader worker(s) "
                        f"{[p.pid for p in crashed]} exited with "
                        f"{[p.exitcode for p in crashed]} before finishing")
                if q.qsize() == 0 and not any(p.is_alive() for p in procs):
                    raise RuntimeError(
                        f"DataLoader workers all exited but only "
                        f"{received}/{n} batches arrived")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"DataLoader stalled: {received}/{n} batches after "
                        f"{deadline_s:.0f}s without progress")
                continue
            if i == -1:  # worker shipped its traceback
                raise RuntimeError(payload)
            pending[i] = payload
            received += 1
            while next_idx in pending:
                yield _from_numpy_tree(pending.pop(next_idx))
                next_idx += 1
    finally:
        q.close()
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()


def _to_numpy_tree(obj):
    from ..core.tensor import Tensor
    import numpy as np

    if isinstance(obj, Tensor):
        return ("T", np.asarray(obj._value))
    if isinstance(obj, (list, tuple)):
        return ("L", type(obj).__name__,
                [_to_numpy_tree(v) for v in obj])
    if isinstance(obj, dict):
        return ("D", {k: _to_numpy_tree(v) for k, v in obj.items()})
    return ("V", obj)


def _from_numpy_tree(node):
    from ..core.tensor import Tensor

    tag = node[0]
    if tag == "T":
        return Tensor(node[1])
    if tag == "L":
        seq = [_from_numpy_tree(v) for v in node[2]]
        return tuple(seq) if node[1] == "tuple" else seq
    if tag == "D":
        return {k: _from_numpy_tree(v) for k, v in node[1].items()}
    return node[1]
