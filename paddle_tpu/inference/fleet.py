"""ReplicaFleet: launch, supervise, and heal N InferenceServer replicas.

The process-lifecycle half of the fleet serving story (ISSUE 9; the
routing half is `inference/router.py`).  A `ReplicaFleet`:

  * **launches** `num_replicas` replica subprocesses (one per chip
    slice in a real deployment; `python -m paddle_tpu.inference.fleet
    --replica ...`), each an ordinary `InferenceServer` with a
    `PreemptionGuard` installed — every single-process reflex from
    PRs 3/5/8 (admission control, graceful drain, engine cancel/
    reclaim) is reused verbatim inside each replica.
  * **watches liveness two ways**: process exit (immediate
    `router.note_replica_down`) and `fleet/elastic.py` heartbeats —
    each replica registers an `ElasticManager` beating into the
    fleet's TCPStore; the router ejects a replica that misses
    `heartbeat_miss_k` beats even when its process is merely wedged.
  * **relaunches** dead replicas (bounded by `max_restarts` per rank)
    and re-points the router at the new address; the router re-admits
    the replica only after it passes readiness.
  * **drains deliberately**: `drain_replica(rank)` takes the replica
    out of the router's rotation FIRST, waits for router-side
    in-flight traffic toward it to reach zero, and only then delivers
    SIGTERM — the replica's own `PreemptionGuard` finishes in-flight
    work and exits 0.  No thundering 503s, no severed requests.
  * **resizes at runtime** (ISSUE 14): `add_replica()` grows the fleet
    by one (fresh rank, spawned + announced + registered with the
    router, readiness-gated into rotation like any launch) and
    `remove_replica(rank)` shrinks it through the zero-loss drain
    protocol above, then retires the rank — the monitor never
    relaunches a removed rank, and `stop()` sweeps whatever membership
    exists at stop time, not the `__init__` roster.  The
    `inference.autoscaler.Autoscaler` drives both off the SLO burn
    rate and edge-admission occupancy.

Replica kinds (`--kind`): `echo` (stdlib+numpy predict-only stub —
fast startup, the unit/chaos workhorse), `toy` (echo predict + the
deterministic `ToyEngine` token streamer, for /generate failover
proofs without jax), `gpt` (a real paged-KV `InferenceEngine` over a
small seeded GPT — the bench path), `model` (a saved-model predictor
via `--model-path`).

Env knobs:
  PADDLE_TPU_FLEET_REPLICAS     default replica count           (2)
  PADDLE_TPU_HEARTBEAT_MISS_K   router ejection threshold       (3)
  PADDLE_TPU_FAILOVER_RETRIES   router failover budget          (2)

Chaos fault point `replica.crash` fires every replica main-loop tick:
kind="error" exits the replica non-zero (a crash); any other kind is
an immediate `os._exit(137)` — a simulated kill -9.
"""
from __future__ import annotations

import json
import os
import queue
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid

import numpy as np

from ..observability import lifecycle as _lifecycle
from ..resilience.overload import _env_num

__all__ = ["ReplicaFleet", "ToyEngine", "EchoPredictor", "toy_token"]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# deterministic stand-ins (tests / chaos / any-process parity)
# ---------------------------------------------------------------------------

class EchoPredictor:
    """Stdlib+numpy predictor: sleeps `service_time` then echoes its
    input — deterministic across replicas, so a failed-over request's
    response is bit-identical to the one the dead replica would have
    sent."""

    def __init__(self, service_time=0.0):
        self.service_time = float(service_time)

    def get_input_names(self):
        return ["x"]

    def get_output_names(self):
        return ["y"]

    def run(self, inputs):
        if self.service_time:
            time.sleep(self.service_time)
        return [np.asarray(inputs[0])]


def toy_token(prompt_ids, i):
    """The ToyEngine's token function: a pure function of (prompt,
    position), identical in every process — so chaos can verify that a
    failed-over or interrupted stream delivered EXACTLY a prefix of
    the true sequence (any replayed or skipped token breaks the
    position-dependent pattern)."""
    s = sum(int(x) for x in prompt_ids) % 9973
    return (7919 * s + 131 * int(i) + 17 * len(prompt_ids)) % 997


class _ToyHandle:
    """Duck-type of engine.RequestHandle: token queue + completion."""

    def __init__(self, request_id):
        self.request_id = request_id
        self.tenant_id = None
        self.done = threading.Event()
        self.finish_reason = None
        self.cancelled = False
        self.cache_state = "miss"   # no prefix cache in the toy engine
        self.tokens = []
        self._prompt = []
        self._q = queue.Queue()

    def _finish(self, reason):
        if self.done.is_set():
            return
        self.finish_reason = reason
        self.done.set()
        self._q.put(None)

    def stream(self, timeout=120.0):
        while True:
            tok = self._q.get(timeout=timeout)  # queue.Empty → caller
            if tok is None:
                return
            yield tok

    def result(self, timeout=300.0):
        if not self.done.wait(timeout=timeout):
            raise TimeoutError(f"toy request {self.request_id} not done")
        return np.asarray(list(self._prompt) + list(self.tokens),
                          np.int32)


class _ToyConfig:
    def __init__(self, max_slots):
        self.max_slots = int(max_slots)


class ToyEngine:
    """Deterministic, jax-free engine duck-type behind POST /generate:
    one daemon thread per sequence emits `toy_token(prompt, i)` every
    `token_time` seconds.  Exists so router/fleet failover semantics
    are provable in fast tier-1 tests and cross-process chaos without
    compiling a model; the real `inference.engine.InferenceEngine`
    drops in unchanged (`--kind gpt`)."""

    def __init__(self, max_slots=4, token_time=0.01):
        self.config = _ToyConfig(max_slots)
        self.token_time = float(token_time)
        self._lock = threading.Lock()
        self._handles = {}
        self._active = 0
        self._stopped = False
        # tenant metering parity with the real engine (ISSUE 16): the
        # toy fleet's chaos runs gate the conservation invariant, so
        # the toy engine must keep the same per-tenant decode books
        # (record_decode owns the engine.tokens increment)
        from ..observability import metrics as _metrics
        from ..observability import tenant_ledger as _tledger

        self.tenant_ledger = _tledger.TenantLedger() \
            if _tledger.enabled() and _metrics.enabled() else None

    def start(self):
        return self

    def stop(self, timeout=5.0):
        with self._lock:
            self._stopped = True
            handles = list(self._handles.values())
        for h in handles:
            h.cancelled = True
            h._finish("cancelled")

    def submit(self, input_ids, max_new_tokens=32, eos_token_id=None,
               request_id=None, tenant_id=None, priority_class=None,
               deadline=None, prebilled_tokens=0):
        # priority_class / deadline / prebilled_tokens are accepted for
        # signature parity with the real engine (serving passes them
        # through uniformly); the toy engine has no scheduler to
        # preempt or shed, so it honors only the billing marker —
        # chaos gates the conservation invariant against toy books too
        ids = [int(x) for x in np.asarray(input_ids).reshape(-1)]
        if not ids:
            raise ValueError("empty input_ids")
        h = _ToyHandle(request_id or uuid.uuid4().hex[:16])
        h.tenant_id = tenant_id
        h.priority_class = priority_class
        h._prompt = ids
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine stopped")
            self._handles[h.request_id] = h
            self._active += 1

        def _run():
            try:
                for i in range(int(max_new_tokens)):
                    if h.cancelled:
                        h._finish("cancelled")
                        return
                    if self.token_time:
                        time.sleep(self.token_time)
                    tok = toy_token(ids, i)
                    h.tokens.append(tok)
                    if i < int(prebilled_tokens):
                        pass  # resume verify token: billed by the
                        # replica that died (ISSUE 20), never twice
                    elif self.tenant_ledger is not None:
                        self.tenant_ledger.record_decode(tenant_id)
                    h._q.put(tok)
                    if eos_token_id is not None and tok == eos_token_id:
                        h._finish("eos")
                        return
                h._finish("length")
            finally:
                with self._lock:
                    self._active -= 1
                    self._handles.pop(h.request_id, None)

        threading.Thread(target=_run, daemon=True,
                         name=f"toy-seq-{h.request_id[:6]}").start()
        return h

    def cancel(self, request_id):
        with self._lock:
            h = self._handles.get(request_id)
        if h is None:
            return False
        h.cancelled = True
        h._finish("cancelled")
        return True

    def stats(self):
        with self._lock:
            n = self._active
        m = self.config.max_slots
        return {"running": n, "waiting": 0, "max_slots": m,
                "occupancy": n / m, "steps": 0, "pages": {}}


# ---------------------------------------------------------------------------
# the fleet supervisor
# ---------------------------------------------------------------------------

class _ReplicaHandle:
    """One supervised replica slot (rank is stable across relaunches)."""

    __slots__ = ("rank", "rid", "proc", "address", "announce",
                 "restarts", "drain_requested", "log_path", "removed")

    def __init__(self, rank):
        self.rank = int(rank)
        self.rid = f"r{rank}"
        self.proc = None
        self.address = None
        self.announce = None
        self.restarts = 0
        self.drain_requested = False
        self.log_path = None
        self.removed = False   # retired rank: exit is final, no relaunch


class ReplicaFleet:
    """Launch and supervise a replica fleet behind a `Router`.

    `start()` spawns the replicas, waits for each to announce its
    address, starts the router (synchronous first probe), and begins
    the monitor loop.  `stop()` drains the router, SIGTERMs every
    replica, and reaps them.  See the module docstring for semantics.
    """

    def __init__(self, num_replicas=None, kind="echo", model_path=None,
                 router=None, router_kwargs=None, service_time=0.0,
                 token_time=0.01, max_slots=4, request_timeout=30.0,
                 heartbeat=True, heartbeat_interval=0.4,
                 heartbeat_ttl=1.6, max_restarts=3,
                 monitor_interval=0.15, launch_timeout=60.0,
                 workdir=None, replica_env=None, spawner=None,
                 telemetry_dir=None):
        if num_replicas is None:
            num_replicas = _env_num("PADDLE_TPU_FLEET_REPLICAS", 2, int)
        self.num_replicas = max(1, int(num_replicas))
        self.kind = str(kind)
        self.model_path = model_path
        self.service_time = float(service_time)
        self.token_time = float(token_time)
        self.max_slots = int(max_slots)
        self.request_timeout = float(request_timeout)
        self.heartbeat = bool(heartbeat)
        self.heartbeat_interval = float(heartbeat_interval)
        self.heartbeat_ttl = float(heartbeat_ttl)
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.launch_timeout = float(launch_timeout)
        self.workdir = workdir
        self.replica_env = dict(replica_env or {})
        self.telemetry_dir = telemetry_dir
        self._spawner = spawner or self._spawn_subprocess
        self.job_id = f"fleet-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._handles = {r: _ReplicaHandle(r)
                         for r in range(self.num_replicas)}
        self._next_rank = self.num_replicas  # dynamic growth cursor
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._monitor_thread = None
        self.store = None
        self._store_is_master = False
        self._store_addr = None
        self._elastic = None
        self.events = []  # ordered lifecycle log (tests assert on it)
        # spawn-to-routable phase records (ISSUE 17): the supervisor
        # stamps what only it can see (Popen, announce file observed);
        # the router stamps first_probe_up/first_routable_request and
        # attaches each replica's own ledger record at first probe-up
        self.lifecycle = _lifecycle.FleetLifecycle()
        if router is not None:
            self.router = router
        else:
            from .router import Router

            kw = dict(router_kwargs or {})
            kw.setdefault("request_timeout", self.request_timeout)
            self.router = Router(**kw)
        self.router.lifecycle = self.lifecycle

    # --- heartbeat plumbing (fleet/elastic.py reuse) ----------------------
    def _start_store(self):  # pt-lint: ok[PT503] (startup phase: runs once from start() before the monitor/relaunch threads exist; the heartbeats escape on the last line is the publish barrier)
        """TCPStore master for the heartbeat registry; replicas beat
        through their own `ElasticManager`.  Heartbeats are an extra
        liveness signal, not a hard dependency — when the native store
        cannot start (port exhaustion, missing lib) the fleet degrades
        to process-exit + readiness-probe liveness only."""
        if not self.heartbeat:
            return
        try:
            from ..distributed.fleet.elastic import ElasticManager
            from ..distributed.store import TCPStore
        except Exception as e:  # pt-lint: ok[PT005]
            self._event("store_unavailable", error=type(e).__name__)
            return  # degrade: no heartbeat plane (reason logged above)
        base = 19000 + (os.getpid() * 7) % 20000
        for k in range(16):
            port = base + k * 13
            try:
                self.store = TCPStore("127.0.0.1", port, is_master=True)
                self._store_is_master = True
                self._store_addr = f"127.0.0.1:{port}"
                break
            except Exception:  # pt-lint: ok[PT005]
                continue       # port taken: probe the next candidate
        if self.store is None:
            self._event("store_unavailable", error="no_free_port")
            return
        self._elastic = ElasticManager(
            store=self.store, job_id=self.job_id,
            np_range=str(self.num_replicas),
            heartbeat_interval=self.heartbeat_interval,
            heartbeat_ttl=self.heartbeat_ttl)
        self.router.heartbeats = self._alive_replicas

    def _alive_replicas(self):
        """Replica ids with fresh heartbeats — the router's heartbeat
        source.  Reads the same `elastic/<job>/hb/<rank>` keys the
        replicas' ElasticManagers write, with a short per-key timeout
        so a missing rank costs milliseconds, not the elastic default
        blocking get."""
        alive = set()
        if self.store is None or self._elastic is None:
            return alive
        now = time.time()
        with self._lock:
            ranks = list(self._handles)  # live membership, not the
            # __init__ roster: a dynamically-added rank must be able to
            # beat, a removed rank must stop being asked after
        for r in ranks:
            key = self._elastic._hb_key(r)
            try:
                if not self.store.check(key):
                    continue
                ts = float(self.store.get(key, timeout=0.1))
            except Exception:  # pt-lint: ok[PT005]
                continue       # absent/failed key IS the miss signal
            if now - ts <= self.heartbeat_ttl:
                alive.add(f"r{r}")
        return alive

    # --- spawning ---------------------------------------------------------
    def _replica_cmd(self, handle):
        cmd = [sys.executable, "-m", "paddle_tpu.inference.fleet",
               "--replica", "--rank", str(handle.rank),
               "--kind", self.kind,
               "--announce", handle.announce,
               "--job-id", self.job_id,
               "--service-time", str(self.service_time),
               "--token-time", str(self.token_time),
               "--max-slots", str(self.max_slots),
               "--request-timeout", str(self.request_timeout),
               "--heartbeat-interval", str(self.heartbeat_interval),
               "--heartbeat-ttl", str(self.heartbeat_ttl)]
        if self._store_addr:
            cmd += ["--store", self._store_addr]
        if self.model_path:
            cmd += ["--model-path", str(self.model_path)]
        return cmd

    def _replica_environ(self, handle):
        env = dict(os.environ)
        env.update(self.replica_env)
        env["PADDLE_TRAINER_ID"] = str(handle.rank)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        if self.telemetry_dir:
            env["PADDLE_TPU_TELEMETRY_DIR"] = str(self.telemetry_dir)
        return env

    def _spawn_subprocess(self, handle, cmd, env):
        log = open(handle.log_path, "ab")
        try:
            return subprocess.Popen(cmd, env=env, stdout=log,
                                    stderr=subprocess.STDOUT,
                                    cwd=_REPO_ROOT)
        finally:
            log.close()  # the child holds its own fd

    def _launch(self, handle):
        """Spawn one replica process.  The fork+exec runs OUTSIDE the
        fleet lock (it costs tens of milliseconds — holding the lock
        across it stalls the monitor sweep and every router membership
        change behind process creation), but the anti-orphan invariant
        vs `stop()` still holds: the proc is installed under the lock
        with a stopping re-check, and when stop() won the race — its
        sweep snapshot cannot have seen this proc — the spawner kills
        its own child right here.  Either the sweep owns the process or
        we do; there is no window where nobody does.
        Returns False when stopping."""
        handle.announce = os.path.join(
            self.workdir, f"replica_{handle.rank}"
                          f"_{handle.restarts}.addr")
        handle.log_path = os.path.join(
            self.workdir, f"replica_{handle.rank}.log")
        handle.address = None
        handle.drain_requested = False
        cmd = self._replica_cmd(handle)
        env = self._replica_environ(handle)
        # open the spawn record + pass the supervisor's wall anchor to
        # the child (the cross-process half of the clock-skew join: the
        # child back-dates proc_spawn by the wall delta so its imports
        # phase covers fork + interpreter start)
        spawn_wall = self.lifecycle.spawn(handle.rid, rank=handle.rank)
        env["PADDLE_TPU_SPAWN_WALL"] = f"{spawn_wall:.6f}"
        with self._lock:
            if self._stopping.is_set() or handle.removed:
                return False  # stopping, or the rank was retired while
                # a relaunch was in flight — don't even spawn
        proc = self._spawner(handle, cmd, env)
        with self._lock:
            if not (self._stopping.is_set() or handle.removed):
                handle.proc = proc
                proc = None  # installed: stop()/remove's sweep owns it
        if proc is not None:
            # stop() or remove_replica() raced the spawn: their sweeps
            # never saw this proc, so reaping it is OUR job
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except Exception:  # pt-lint: ok[PT005]
                pass           # already dead / unkillable zombie —
                # nothing more a supervisor can do with it
            return False
        self._event("replica_spawned", rank=handle.rank,
                    restarts=handle.restarts)
        return True

    def _await_announce(self, handle, timeout=None):
        """Block until the replica writes its address file (atomic
        rename), or it dies, or the timeout lapses.  Returns the
        address or None."""
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else self.launch_timeout)
        while time.monotonic() < deadline:
            if self._stopping.is_set():
                return None  # stop() owns teardown from here
            if os.path.exists(handle.announce):
                try:
                    with open(handle.announce) as f:
                        info = json.load(f)
                    handle.address = info["address"]
                    self.lifecycle.stamp(handle.rid, "announce")
                    return handle.address
                except (ValueError, KeyError, OSError):
                    pass  # torn read mid-rename: retry next tick
            if handle.proc is not None and \
                    handle.proc.poll() is not None:
                return None  # died during startup
            time.sleep(0.02)
        return None

    # --- lifecycle --------------------------------------------------------
    def start(self, wait_ready=True, ready_timeout=None):
        # pt-lint: ok[PT503] (startup phase: workdir is pinned before any replica or monitor thread exists, and never rebound after)
        self.workdir = self.workdir or tempfile.mkdtemp(
            prefix="paddle_tpu_fleet_")
        os.makedirs(self.workdir, exist_ok=True)
        self._start_store()
        with self._lock:
            handles = list(self._handles.values())
        for handle in handles:
            self._launch(handle)
        for handle in handles:
            addr = self._await_announce(handle)
            if addr is None:
                raise RuntimeError(
                    f"replica {handle.rid} failed to start "
                    f"(see {handle.log_path})")
            self.router.add_replica(handle.rid, addr)
        self.router.start()
        if wait_ready:
            self.wait_ready(timeout=ready_timeout)
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True,
            name="paddle-tpu-fleet-monitor")
        self._monitor_thread.start()
        return self

    def wait_ready(self, n=None, timeout=None):
        """Block until `n` (default: all) replicas are routable."""
        want = self.num_replicas if n is None else int(n)
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.launch_timeout)
        while time.monotonic() < deadline:
            if self.router.routable_count() >= want:
                return True
            time.sleep(0.05)
        return self.router.routable_count() >= want

    def _monitor(self):
        """Reap loop.  Deliberately non-blocking: deaths are booked
        with the router IMMEDIATELY; the relaunch (whose announce wait
        can take seconds) runs on a helper thread per replica, so one
        wedged relaunch never delays detecting another replica's
        death."""
        relaunching: set = set()
        while not self._stopping.wait(self.monitor_interval):
            with self._lock:
                sweep = list(self._handles.values())
            # the sweep runs over a snapshot: membership may change
            # under it (autoscaler add/remove).  A handle popped
            # mid-sweep has proc=None (skip); a handle added mid-sweep
            # is picked up next tick; a REMOVED rank's exit is final —
            # relaunching it would resurrect what the autoscaler
            # deliberately retired.
            for handle in sweep:
                proc = handle.proc
                if proc is None or handle.rank in relaunching:
                    continue
                rc = proc.poll()
                if rc is None:
                    continue
                # the process is gone: tell the router NOW (faster
                # than aging out K heartbeats), then heal
                self._event("replica_exit", rank=handle.rank, rc=rc,
                            drained=handle.drain_requested)
                self.router.note_replica_down(handle.rid)
                handle.proc = None
                if self._stopping.is_set() or handle.removed:
                    continue
                if handle.restarts >= self.max_restarts:
                    # out of restarts: RETIRE the rank instead of
                    # keeping a corpse on the roster — a dead handle
                    # would inflate replica_count() forever, blocking
                    # the autoscaler's max bound with capacity that
                    # does not exist (it can now add a fresh rank)
                    self._event("replica_abandoned", rank=handle.rank)
                    with self._lock:
                        self._handles.pop(handle.rank, None)
                    self.router.remove_replica(handle.rid)
                    continue
                handle.restarts += 1
                relaunching.add(handle.rank)
                threading.Thread(
                    target=self._relaunch,
                    args=(handle, relaunching.discard), daemon=True,
                    name=f"fleet-relaunch-r{handle.rank}").start()

    def _relaunch(self, handle, done_cb):
        try:
            if not self._launch(handle):
                return  # stopping: stop() owns teardown
            addr = self._await_announce(handle)
            if addr is not None:
                self.router.update_replica(handle.rid, addr)
                self._event("replica_relaunched", rank=handle.rank,
                            address=addr)
            else:
                self._event("replica_relaunch_failed",
                            rank=handle.rank)
        finally:
            done_cb(handle.rank)

    # --- dynamic membership (ISSUE 14: the autoscaler's two verbs) ------
    def replica_count(self):
        """Live fleet size (supervised ranks, whatever their state)."""
        with self._lock:
            return len(self._handles)

    def replica_ranks(self):
        with self._lock:
            return sorted(self._handles)

    def observed_spawn_ms(self):
        """Median observed spawn -> first_probe_up wall over recent
        spawns (ISSUE 17) — what the autoscaler's predictive signal is
        actually buying.  None before any spawn completed."""
        return self.lifecycle.observed_spawn_ms()

    def add_replica(self, timeout=None):
        """Grow the fleet by one replica: fresh rank, spawn, await the
        announce file, register with the router (readiness-gated into
        rotation by the probe loop, like any launch).  Returns the new
        rank, or None when stopping or the launch failed — the failed
        handle leaves the table either way, so a flaky spawn cannot
        leave a rank the monitor supervises but the router never saw."""
        with self._lock:
            if self._stopping.is_set():
                return None
            rank = self._next_rank
            self._next_rank += 1
            handle = _ReplicaHandle(rank)
            self._handles[rank] = handle
        if not self._launch(handle):
            with self._lock:
                self._handles.pop(rank, None)
            return None
        addr = self._await_announce(handle, timeout=timeout)
        if addr is None:
            self._event("replica_add_failed", rank=rank)
            with self._lock:
                # removed BEFORE the pop: a monitor sweep holding this
                # handle in its snapshot must see the retirement, or it
                # would relaunch the dead rank into a process no sweep
                # ever kills and a router entry no handle supervises
                handle.removed = True
                proc = handle.proc
                self._handles.pop(rank, None)
            if proc is not None:
                try:
                    proc.kill()
                    proc.wait(timeout=2.0)
                except Exception:  # pt-lint: ok[PT005]
                    pass  # already gone — which is all we needed
            return None
        self.router.add_replica(handle.rid, addr)
        self._event("replica_added", rank=rank, address=addr)
        return rank

    def remove_replica(self, rank, grace=5.0, exit_timeout=10.0):
        """Shrink the fleet by one replica through the zero-loss drain
        protocol (rotation out → router in-flight to zero → SIGTERM →
        PreemptionGuard drain → exit 0), then retire the rank: the
        monitor never relaunches it and the router forgets it.  Returns
        the replica's exit code (0 for a clean drain), True when the
        rank retired but its process was already gone (nothing to
        reap), or None when the rank is unknown — callers branch on
        `is None` to tell "removed nothing" from "removed".  A process
        that outlives `exit_timeout` is killed — the rank retires
        either way."""
        with self._lock:
            handle = self._handles.get(int(rank))
            if handle is None:
                return None
            handle.removed = True  # from here the exit is final
            # capture the process HERE, in the same critical section:
            # after the drain below the monitor may have reaped the
            # exit and nulled handle.proc, and reading it then would
            # lose the exit code a clean drain must report (rc=0)
            proc = handle.proc
        self.drain_replica(rank, grace=grace)
        rc = None
        if proc is not None:
            try:
                rc = proc.wait(timeout=exit_timeout)
            except Exception:  # pt-lint: ok[PT005]
                try:           # (drain overran its grace: hard stop —
                    proc.kill()      # the rank is leaving regardless)
                    rc = proc.wait(timeout=2.0)
                except Exception:  # pt-lint: ok[PT005]
                    pass           # unkillable == already a zombie
        with self._lock:
            handle.proc = None
            self._handles.pop(int(rank), None)
        self.router.remove_replica(handle.rid)
        self._event("replica_removed", rank=handle.rank, rc=rc)
        return rc if proc is not None else True

    def drain_replica(self, rank, grace=5.0):
        """Deliberate drain of one replica, in the safe order: router
        rotation OUT first, router-side in-flight toward it to zero
        (bounded by `grace`), THEN SIGTERM — the replica's
        PreemptionGuard handles the rest (finish in-flight, exit 0).
        The monitor relaunches it afterward (capacity heals)."""
        with self._lock:
            handle = self._handles.get(int(rank))
        if handle is None:
            return False  # retired/unknown rank: a drain is a no-op,
            # not a KeyError (ranks can now leave the table at runtime)
        self._event("drain_mark", rank=handle.rank)
        self.router.mark_draining(handle.rid)
        deadline = time.monotonic() + float(grace)
        while self.router.inflight_to(handle.rid) > 0 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        handle.drain_requested = True
        self._event("drain_sigterm", rank=handle.rank)
        if handle.proc is not None:
            try:
                handle.proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):  # pt-lint: ok[PT005]
                pass  # already gone: the monitor will book the exit
        return True

    def kill_replica(self, rank):
        """Hard kill (SIGKILL) — the chaos path.  No drain, no mercy;
        the router's failover owns the consequences."""
        with self._lock:
            handle = self._handles.get(int(rank))
        if handle is None:
            return False  # already retired: as dead as kill would
            # have made it
        self._event("kill", rank=handle.rank)
        if handle.proc is not None:
            try:
                handle.proc.kill()
            except (ProcessLookupError, OSError):  # pt-lint: ok[PT005]
                pass  # already dead — which is what we wanted
        return True

    def stop(self, timeout=10.0):
        self._stopping.set()
        with self._lock:
            # barrier: an in-flight _launch finishes its spawn before
            # the sweep below runs; later ones refuse (see _launch).
            # The sweep itself runs over a SNAPSHOT: membership can
            # shrink concurrently (an autoscaler remove_replica mid
            # stop pops its handle), and iterating the live dict would
            # either skip a replica or die on the mutation — either way
            # an orphan.  The snapshot covers every rank alive at the
            # barrier, including dynamically-added ones.
            handles = list(self._handles.values())
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2.0)
        for handle in handles:
            if handle.proc is not None and handle.proc.poll() is None:
                try:
                    handle.proc.send_signal(signal.SIGTERM)
                except (ProcessLookupError, OSError):  # pt-lint: ok[PT005]
                    pass  # raced its own exit
        deadline = time.monotonic() + float(timeout)
        for handle in handles:
            if handle.proc is None:
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                handle.proc.wait(timeout=remaining)
            except Exception:  # pt-lint: ok[PT005]
                try:           # (drain overran its grace: hard stop —
                    handle.proc.kill()   # stop() must return)
                    handle.proc.wait(timeout=2.0)
                except Exception:  # pt-lint: ok[PT005]
                    pass           # unkillable == already a zombie
        self.router.shutdown()
        if self._elastic is not None:
            self._elastic.stop()
        self.store = None
        return True

    def _event(self, kind, **data):
        row = dict(data, kind=kind, t=time.time())
        with self._lock:
            self.events.append(row)
        try:
            from ..observability import flight as _flight

            _flight.record(f"fleet.{kind}", **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: supervision
            # must supervise even when telemetry is broken)

    def describe(self):
        with self._lock:
            handles = {
                h.rid: {"rank": h.rank, "address": h.address,
                        "restarts": h.restarts,
                        "alive": h.proc is not None
                        and h.proc.poll() is None}
                for h in self._handles.values()}
        return {"job_id": self.job_id, "replicas": handles,
                "router": self.router.replica_summary()}


# ---------------------------------------------------------------------------
# replica entry point (python -m paddle_tpu.inference.fleet --replica)
# ---------------------------------------------------------------------------

def _build_gpt_engine(seed=0, max_slots=4):
    """A real continuous-batching engine over a small seeded GPT — the
    same model every replica builds (same seed → same weights → greedy
    decode is replica-independent, so failover changes nothing about
    the tokens a client sees)."""
    import paddle_tpu as P
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from .engine import EngineConfig, InferenceEngine

    P.seed(seed)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=96)
    model = GPTForCausalLM(cfg)
    model.eval()
    return InferenceEngine(model, EngineConfig(
        page_size=8, max_slots=max_slots, decode_chunk=2,
        max_seq_len=96))


def _replica_main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(prog="paddle_tpu.inference.fleet")
    ap.add_argument("--replica", action="store_true", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--kind", default="echo",
                    choices=("echo", "toy", "gpt", "model"))
    ap.add_argument("--announce", required=True)
    ap.add_argument("--job-id", default="fleet")
    ap.add_argument("--store", default=None)
    ap.add_argument("--model-path", default=None)
    ap.add_argument("--service-time", type=float, default=0.0)
    ap.add_argument("--token-time", type=float, default=0.01)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--request-timeout", type=float, default=30.0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.4)
    ap.add_argument("--heartbeat-ttl", type=float, default=1.6)
    args = ap.parse_args(argv)

    from .. import observability as obs
    from ..resilience import faults as _faults
    from .serving import InferenceServer

    obs.attach(crash_hook=False)
    # lifecycle (ISSUE 17): anchor at the supervisor's Popen wall time
    # (PADDLE_TPU_SPAWN_WALL) so the imports phase covers fork +
    # interpreter start + the imports above, then stamp each startup
    # phase on THIS process's monotonic clock
    led = obs.lifecycle.get_ledger()
    led.begin(spawn_wall=os.environ.get("PADDLE_TPU_SPAWN_WALL"))
    led.stamp("imports")
    predictor = engine = None
    if args.kind in ("echo", "toy"):
        predictor = EchoPredictor(service_time=args.service_time)
    if args.kind == "toy":
        engine = ToyEngine(max_slots=args.max_slots,
                           token_time=args.token_time)
    elif args.kind == "gpt":
        engine = _build_gpt_engine(seed=0, max_slots=args.max_slots)
    elif args.kind == "model":
        pass  # model_path below builds the predictor inside the server
    led.stamp("weight_load")

    srv = InferenceServer(
        model_path=args.model_path if args.kind == "model" else None,
        predictor=predictor, engine=engine,
        request_timeout=args.request_timeout)
    guard = srv.install_preemption()

    elastic = None
    if args.store:
        try:
            from ..distributed.fleet.elastic import ElasticManager
            from ..distributed.store import TCPStore

            host, port = args.store.rsplit(":", 1)
            store = TCPStore(host, int(port), is_master=False)
            elastic = ElasticManager(
                store=store, job_id=args.job_id,
                np_range=str(args.rank + 1),
                heartbeat_interval=args.heartbeat_interval,
                heartbeat_ttl=args.heartbeat_ttl)
            elastic.rank = args.rank
            elastic.register()
        except Exception as e:
            # a replica without a heartbeat plane still serves: the
            # router falls back to probe liveness for it.  Say so.
            print(f"replica {args.rank}: heartbeat disabled "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
            elastic = None

    exporter = None
    if os.environ.get("PADDLE_TPU_TELEMETRY_DIR"):
        from ..observability.export import TelemetryExporter

        exporter = TelemetryExporter(
            slo=srv.slo.report, rank=args.rank,
            # per-tenant ledger (ISSUE 16): each replica dumps its own
            # book; telemetry_agg merges them into the fleet rollup
            tenants=(srv.tenant_ledger.snapshot
                     if srv.tenant_ledger is not None else None),
            # per-request timelines (ISSUE 15): real engines expose
            # them; toy duck-types simply don't ship the key
            timelines=getattr(srv.engine, "recent_timelines", None),
            # lifecycle record (ISSUE 17): each dump carries this
            # replica's spawn-phase story; full state, last dump wins
            lifecycle=led.record).start()

    srv.start()
    # warm up BEFORE announcing (ISSUE 17): a tiny generate triggers
    # the engine's jit compiles so "routable" means "warm" — the
    # compile cost lands in the warmup phase (attributed per program
    # by xla_cost.instrument) instead of the first client request.
    # PADDLE_TPU_REPLICA_WARMUP=0 restores announce-first behavior.
    if os.environ.get("PADDLE_TPU_REPLICA_WARMUP", "1") != "0" \
            and args.kind == "gpt" and engine is not None:
        try:
            engine.generate([np.arange(1, 5, dtype=np.int32)],
                            max_new_tokens=2)
        except Exception as e:
            print(f"replica {args.rank}: warmup failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
    led.stamp("warmup")
    tmp = args.announce + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"address": srv.address, "pid": os.getpid(),
                   "rank": args.rank}, f)
    os.replace(tmp, args.announce)  # atomic: no torn reads
    led.stamp("announce")

    try:
        while not guard.preempted:
            # the chaos hook: kind="error" → crash (non-zero exit);
            # any other kind → simulated kill -9
            try:
                act = _faults.fire("replica.crash", rank=args.rank)
            except _faults.InjectedFault:
                sys.exit(1)
            if act is not None:
                os._exit(137)
            guard.wait(timeout=0.25)
    finally:
        srv.shutdown()
        if elastic is not None:
            elastic.stop()
        if exporter is not None:
            exporter.stop()
    print(f"replica {args.rank} drained ({guard.reason})", flush=True)


if __name__ == "__main__":
    _replica_main()
