"""QoS priority classes: the shared vocabulary of the multi-tenant
policy plane (ISSUE 18 / ROADMAP item 4).

Three classes, strictly ordered:

  =======  ====  =======================================================
  class    rank  promise
  =======  ====  =======================================================
  paid      2    holds its p99 under surge; shed LAST, preempts others
  free      1    best-effort; degrades via counted sheds + warm
                 preemption before paid feels anything
  batch     0    throughput scavenger; first shed, first preempted,
                 aging-bounded so it still eventually runs
  =======  ====  =======================================================

Every layer prices the same ordering differently:

  * the **edge** (`AdmissionController`) queues/sheds lowest class
    first (nested weighted queue partitions + strict-priority dequeue
    with an aging knob) and hands lower classes honest longer
    `Retry-After` backoff;
  * the **scheduler** preempts the lowest-class youngest sequence via
    the recompute-eviction path (warm resume since ISSUE 13);
  * the **SLO tracker** keeps per-class burn so the autoscaler scales
    for the paid tier while free absorbs the shed.

Class identity arrives on `X-Priority-Class` (validate-or-drop, like
every identity header), defaults per tenant via the
`PADDLE_TPU_QOS_CLASSES` map (``tenant-0:paid,team-*:batch,*:free``),
and falls back to `DEFAULT_CLASS`.

stdlib-only and import-cycle-free: observability and inference both
import this.
"""
from __future__ import annotations

import fnmatch
import os

__all__ = [
    "CLASSES", "DEFAULT_CLASS", "class_rank", "normalize_class",
    "class_map_from_env", "resolve_class", "retry_after_factor",
    "class_weight", "ENV_CLASS_MAP", "ENV_RESUME_CLASSES",
    "resume_classes_from_env",
]

# strict order, highest first — rank = distance from the end
CLASSES = ("paid", "free", "batch")
DEFAULT_CLASS = "free"
ENV_CLASS_MAP = "PADDLE_TPU_QOS_CLASSES"
# which classes the router's mid-stream resume (ISSUE 20) serves:
# comma-separated class names; unset/empty = every class.  The knob
# exists so an operator can declare `batch` streams not worth the
# resume re-prefill — they fall back to the clean `interrupted` record
ENV_RESUME_CLASSES = "PADDLE_TPU_STREAM_RESUME_CLASSES"

_RANK = {c: len(CLASSES) - 1 - i for i, c in enumerate(CLASSES)}

# decode-slot / queue-share weights (fairness is priced in the
# ledger's decode-slot-ms unit; these are the relative shares)
_WEIGHT = {"paid": 4.0, "free": 2.0, "batch": 1.0}

# Retry-After multipliers: a shed free/batch client backs off honestly
# longer than a paid one under the same pressure estimate
_RETRY_FACTOR = {"paid": 1.0, "free": 2.0, "batch": 4.0}


def class_rank(cls) -> int:
    """Numeric priority (higher = more important).  Unknown/None maps
    to the default class's rank — rank is for ORDERING, normalization
    for validation."""
    return _RANK.get(cls, _RANK[DEFAULT_CLASS])


def normalize_class(value):
    """Validate-or-drop: the class name if `value` is a known class
    (case-insensitive, surrounding whitespace tolerated), else None.
    A garbage `X-Priority-Class` must not mint a garbage label."""
    if value is None:
        return None
    v = str(value).strip().lower()
    return v if v in _RANK else None


def class_weight(cls) -> float:
    return _WEIGHT.get(cls, _WEIGHT[DEFAULT_CLASS])


def retry_after_factor(cls) -> float:
    return _RETRY_FACTOR.get(cls, _RETRY_FACTOR[DEFAULT_CLASS])


def class_map_from_env(env=None) -> list:
    """Parse `PADDLE_TPU_QOS_CLASSES` into an ordered list of
    (tenant-pattern, class) rules.  Format: comma-separated
    ``pattern:class`` entries; patterns are fnmatch-style (so ``*``
    and ``team-*`` work); first match wins.  Malformed entries and
    unknown classes are dropped, not raised — a bad env var must not
    take the edge down."""
    raw = (env if env is not None
           else os.environ.get(ENV_CLASS_MAP, "")) or ""
    rules = []
    for part in raw.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        pattern, _, cls = part.rpartition(":")
        cls = normalize_class(cls)
        pattern = pattern.strip()
        if not pattern or cls is None:
            continue
        rules.append((pattern, cls))
    return rules


def resume_classes_from_env(env=None) -> frozenset:
    """Parse `PADDLE_TPU_STREAM_RESUME_CLASSES` into the set of classes
    eligible for mid-stream resume (ISSUE 20).  Unset or empty means
    ALL classes; unknown names are dropped (validate-or-drop, like
    every class input) — and if every entry is garbage the policy
    falls back to all-classes rather than silently disabling resume
    fleet-wide on a typo."""
    raw = (env if env is not None
           else os.environ.get(ENV_RESUME_CLASSES, "")) or ""
    if not raw.strip():
        return frozenset(CLASSES)
    picked = frozenset(
        c for c in (normalize_class(p) for p in raw.split(","))
        if c is not None)
    return picked or frozenset(CLASSES)


def resolve_class(tenant_id=None, explicit=None, rules=None):
    """The one resolution order every edge uses: an explicit (already
    validated) class wins, else the tenant→class map, else
    `DEFAULT_CLASS`."""
    cls = normalize_class(explicit)
    if cls is not None:
        return cls
    if rules is None:
        rules = class_map_from_env()
    if tenant_id is not None and rules:
        tid = str(tenant_id)
        for pattern, cls in rules:
            if fnmatch.fnmatchcase(tid, pattern):
                return cls
    return DEFAULT_CLASS
