"""SLO-driven fleet autoscaling: close the loop nobody closed.

The fleet (inference/fleet.py) load-balances a FIXED N replicas and
the SLOTracker (observability/slo.py) computes an error-budget burn
rate nobody acts on: a traffic step either sheds forever or idles
capacity.  The `Autoscaler` (ISSUE 14, ROADMAP item 5) closes the
loop:

  * **signals** — the router's fleet-level SLO burn rate (`Router.slo`:
    edge sheds and unsaved failures burn budget there even when every
    replica's own ledger is clean) and the edge admission occupancy
    ((inflight+queued)/limit over both endpoint controllers).  Both
    already exist; the autoscaler only reads.
  * **scale up** on SUSTAINED burn (≥ `burn_up` for `up_sustain`
    consecutive ticks) or sustained occupancy above the high-water
    mark (`occ_up`): `fleet.add_replica()` — spawn, announce,
    readiness-gated into rotation by the router's probe loop.
  * **scale down** on sustained idle (occupancy ≤ `occ_down` AND burn
    below `burn_up` for `down_sustain` ticks):
    `fleet.remove_replica(rank)` — which routes EXCLUSIVELY through
    the zero-loss drain protocol (mark-draining → router in-flight →
    0 → SIGTERM → PreemptionGuard drain → exit 0).  The victim is the
    LEAST affinity-hot routable replica: draining the replica most
    prefix fingerprints are warm on would trade those tenants' TTFT
    for nothing (`Router.affinity_counts`).
  * **hysteresis** — the sustain streaks ask for consecutive evidence
    (one noisy probe can't flap the fleet), and a `cooldown_s` window
    after every action lets the last decision's effect land before
    the next is considered.  Replica count is clamped to
    [`min_replicas`, `max_replicas`] always.

Telemetry (attach() schema): `autoscaler.replicas{state=target|actual}`
gauges and `autoscaler.decisions{action=up|up_predictive|down|hold}`
counters, both visible in `/debug/telemetry` and the `telemetry_agg`
rollup next to `router.capacity{endpoint}`.  Every decision lands in `self.events`
(ordered, like `ReplicaFleet.events`) and as `autoscaler.*` flight
events.

  * **predictive scale-up** (ISSUE 15, ROADMAP item 5's last gap) —
    burn is LAGGING by one SLO window: by the time the budget burns,
    the queue already ate the latency.  Every tick records occupancy
    and queue depth into a bounded `timeseries.TimeSeries` (the same
    injectable clock), and a SUSTAINED positive least-squares slope —
    occupancy growing ≥ `deriv_up`/s (or queue depth ≥
    `queue_deriv_up`/s) while occupancy is already past `deriv_floor`
    — fires a scale-up BEFORE the burn/occupancy thresholds cross,
    through the SAME sustain/cooldown machinery, counted as
    `autoscaler.decisions{action=up_predictive}` and logged as a
    `scale_up_predictive` event.  The first time burn crosses
    `burn_up` a `burn_threshold_crossed` event lands in the log, so
    the surge chaos can assert the predictive scale-up strictly
    preceded the burn-only trigger within one run.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_AUTOSCALE_MIN         lower replica bound           (1)
  PADDLE_TPU_AUTOSCALE_MAX         upper replica bound           (4)
  PADDLE_TPU_AUTOSCALE_COOLDOWN_S  post-action quiet window      (5.0)
  PADDLE_TPU_AUTOSCALE_BURN_UP     burn rate that demands growth (3.0)
  PADDLE_TPU_AUTOSCALE_OCC_UP      occupancy high-water mark     (0.8)
  PADDLE_TPU_AUTOSCALE_OCC_DOWN    occupancy idle mark           (0.2)
  PADDLE_TPU_AUTOSCALE_DERIV_UP    occupancy slope (1/s) that
                                   predicts saturation           (0.05)
  PADDLE_TPU_AUTOSCALE_QUEUE_DERIV_UP  queue-depth slope (req/s) (1.5)
  PADDLE_TPU_AUTOSCALE_DERIV_WINDOW_S  slope fit window          (5.0)
  PADDLE_TPU_AUTOSCALE_DERIV_FLOOR occupancy below which slopes
                                   are noise, never a signal     (0.3)

`burn_up` defaults to the SLO "ticket" rung (slo._BURN_SLOW): spending
a 30-day budget in ~10 days is the point where capacity — not a human
— should respond; the page rung (14.4) is far too late to start
scaling.  Clock and tick are injectable: tests drive `tick()` directly
under a fake clock (tests/test_autoscaler.py); `start()` runs the same
tick on a daemon thread every `interval` seconds.  The surge chaos
scenario (`tools/chaos_check.py --scenario surge`) proves the whole
loop absorbs a 10× open-loop traffic step with zero admitted-request
failures and drains back to min size with zero replayed tokens.
"""
from __future__ import annotations

import threading
import time

from ..observability import metrics as _metrics
from ..observability.timeseries import TimeSeries
from ..resilience.overload import _env_num

__all__ = ["Autoscaler"]


class Autoscaler:
    """Close the loop between the fleet's SLO/occupancy signals and its
    replica count.  See the module docstring for semantics; `tick()` is
    one decision, `start()`/`stop()` run it periodically."""

    def __init__(self, fleet, min_replicas=None, max_replicas=None,
                 burn_up=None, occ_up=None, occ_down=None,
                 up_sustain=2, down_sustain=6, cooldown_s=None,
                 interval=0.5, drain_grace=5.0, clock=time.monotonic,
                 deriv_up=None, queue_deriv_up=None,
                 deriv_window_s=None, deriv_floor=None):
        if min_replicas is None:
            min_replicas = _env_num("PADDLE_TPU_AUTOSCALE_MIN", 1, int)
        if max_replicas is None:
            max_replicas = _env_num("PADDLE_TPU_AUTOSCALE_MAX", 4, int)
        if cooldown_s is None:
            cooldown_s = _env_num("PADDLE_TPU_AUTOSCALE_COOLDOWN_S",
                                  5.0, float)
        if burn_up is None:
            burn_up = _env_num("PADDLE_TPU_AUTOSCALE_BURN_UP", 3.0,
                               float)
        if occ_up is None:
            occ_up = _env_num("PADDLE_TPU_AUTOSCALE_OCC_UP", 0.8, float)
        if occ_down is None:
            occ_down = _env_num("PADDLE_TPU_AUTOSCALE_OCC_DOWN", 0.2,
                                float)
        if deriv_up is None:
            deriv_up = _env_num("PADDLE_TPU_AUTOSCALE_DERIV_UP", 0.05,
                                float)
        if queue_deriv_up is None:
            queue_deriv_up = _env_num(
                "PADDLE_TPU_AUTOSCALE_QUEUE_DERIV_UP", 1.5, float)
        if deriv_window_s is None:
            deriv_window_s = _env_num(
                "PADDLE_TPU_AUTOSCALE_DERIV_WINDOW_S", 5.0, float)
        if deriv_floor is None:
            deriv_floor = _env_num("PADDLE_TPU_AUTOSCALE_DERIV_FLOOR",
                                   0.3, float)
        self.fleet = fleet
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.burn_up = float(burn_up)
        self.occ_up = float(occ_up)
        self.occ_down = float(occ_down)
        self.up_sustain = max(1, int(up_sustain))
        self.down_sustain = max(1, int(down_sustain))
        self.cooldown_s = float(cooldown_s)
        self.interval = float(interval)
        self.drain_grace = float(drain_grace)
        self.clock = clock
        self.deriv_up = float(deriv_up)
        self.queue_deriv_up = float(queue_deriv_up)
        self.deriv_window_s = max(self.interval, float(deriv_window_s))
        self.deriv_floor = float(deriv_floor)
        # the predictive signal's memory: one frame per tick, bounded —
        # the timeseries plane under the same injectable clock
        self.timeseries = TimeSeries(capacity=256, clock=clock)
        self.events = []           # ordered decision log (tests assert)
        self.peak_replicas = 0     # high-water mark the surge gate reads
        self._target = None        # lazily initialised from the fleet
        self._up_streak = 0
        self._pred_streak = 0
        self._down_streak = 0
        self._burn_crossed = False
        self._last_action_t = None
        self._lock = threading.Lock()      # guards self.events only
        self._tick_lock = threading.Lock()  # serializes decisions
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------
    # signals
    # ------------------------------------------------------------------
    def signals(self):
        """One read of the control inputs.  Burn = the worst windowed
        per-endpoint burn rate on the router's fleet-level SLO ledger;
        occupancy = the fuller of the two edge admission controllers,
        (inflight+queued)/limit — above 1.0 means the queue is eating
        into its depth."""
        router = self.fleet.router
        burn = 0.0
        paid_burn = 0.0
        report = router.slo.report(publish_gauges=False)
        for ep in report.get("endpoints", {}).values():
            if ep.get("requests"):
                burn = max(burn, float(ep.get("burn_rate") or 0.0))
            # the paid tier's OWN burn (ISSUE 18): measured against its
            # (usually tighter) class objective.  Under surge the
            # aggregate burn is dominated by deliberately-degraded
            # free/batch sheds — the fleet must still grow when the
            # PAID promise is the one burning.
            crep = (ep.get("classes") or {}).get("paid")
            if crep and crep.get("requests"):
                paid_burn = max(paid_burn,
                                float(crep.get("burn_rate") or 0.0))
        occupancy = 0.0
        queued = 0
        for ctl in (router.admission, router.gen_admission):
            st = ctl.stats()
            queued += int(st["queued"])
            occupancy = max(
                occupancy,
                (st["inflight"] + st["queued"]) / max(1, st["limit"]))
        # observed spawn->routable (ISSUE 17): what a scale-up actually
        # costs right now, measured by the fleet lifecycle plane from
        # completed spawns.  None until the first spawn completed;
        # getattr keeps duck-typed test fleets working unchanged.
        spawn_ms = getattr(self.fleet, "observed_spawn_ms", None)
        spawn_ms = spawn_ms() if callable(spawn_ms) else None
        if spawn_ms is not None:
            spawn_ms = round(float(spawn_ms), 3)
            _metrics.set_gauge("autoscaler.observed_spawn_ms", spawn_ms)
        return {
            "burn_rate": round(burn, 4),
            "paid_burn_rate": round(paid_burn, 4),
            "occupancy": round(occupancy, 4),
            "queue_depth": queued,
            "actual": self.fleet.replica_count(),
            "routable": router.routable_count(),
            "observed_spawn_ms": spawn_ms,
        }

    # ------------------------------------------------------------------
    # the decision
    # ------------------------------------------------------------------
    def tick(self):
        """One control-loop pass: read signals, update the sustain
        streaks, maybe act.  Returns the action taken ("up" | "down" |
        "hold").  Serialized by its own lock — a slow scale action (add
        blocks on announce, remove on drain) never overlaps the next
        tick's decision."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self):  # pt-lint: ok[PT102] (tick holds _tick_lock)
        sig = self.signals()
        actual = sig["actual"]
        if self._target is None:
            self._target = min(self.max_replicas,
                               max(self.min_replicas, actual))
        # feed the timeseries plane FIRST: the slopes below read the
        # frame this tick just recorded
        self.timeseries.record(
            {"occupancy": sig["occupancy"],
             "queue_depth": sig["queue_depth"],
             "burn_rate": sig["burn_rate"],
             "replicas": actual})
        d_occ = self.timeseries.derivative("occupancy",
                                           self.deriv_window_s)
        d_queue = self.timeseries.derivative("queue_depth",
                                             self.deriv_window_s)
        sig["d_occupancy"] = None if d_occ is None else round(d_occ, 4)
        sig["d_queue_depth"] = (None if d_queue is None
                                else round(d_queue, 4))
        if max(sig["burn_rate"], sig["paid_burn_rate"]) >= self.burn_up \
                and not self._burn_crossed:
            # the ordering witness the surge chaos asserts against: a
            # predictive scale-up logged BEFORE this event beat the
            # burn-only trigger within the same run
            self._burn_crossed = True
            self._event("burn_threshold_crossed", **sig)
        # paid-class burn is a first-class scale-up trigger (ISSUE 18):
        # the fleet grows FOR the paid tier — every decision event
        # carries `paid_burn_rate`, so the log shows which promise the
        # action defended
        wants_up = (sig["burn_rate"] >= self.burn_up
                    or sig["paid_burn_rate"] >= self.burn_up
                    or sig["occupancy"] >= self.occ_up)
        # the LEADING signal: pressure not yet over the bar, but
        # growing fast enough that it will be — fire while the launch
        # still lands ahead of the saturation, not one SLO window after
        wants_pred = (sig["occupancy"] >= self.deriv_floor
                      and ((d_occ is not None
                            and d_occ >= self.deriv_up)
                           or (d_queue is not None
                               and d_queue >= self.queue_deriv_up)))
        wants_down = (sig["burn_rate"] < self.burn_up
                      and sig["paid_burn_rate"] < self.burn_up
                      and sig["occupancy"] <= self.occ_down)
        self._up_streak = self._up_streak + 1 if wants_up else 0
        # threshold evidence counts toward the predictive streak too:
        # pressure crossing the bar is the strongest growth evidence
        self._pred_streak = (self._pred_streak + 1
                             if (wants_pred or wants_up) else 0)
        self._down_streak = self._down_streak + 1 if wants_down else 0
        now = self.clock()
        cooled = (self._last_action_t is None
                  or now - self._last_action_t >= self.cooldown_s)
        action = "hold"
        grow = None
        if actual < self.max_replicas and cooled:
            if wants_up and self._up_streak >= self.up_sustain:
                grow = "up"
            elif wants_pred and self._pred_streak >= self.up_sustain:
                grow = "up_predictive"
        if grow is not None:
            rank = self.fleet.add_replica()
            if rank is not None:
                action = grow
                self._target = min(self.max_replicas, actual + 1)
                self._last_action_t = self.clock()  # launch took time
                self._up_streak = 0
                self._pred_streak = 0
                self._event("scale_up" if grow == "up"
                            else "scale_up_predictive", rank=rank,
                            **sig)
            else:
                # the spawn/announce failed: back off for a cooldown
                # anyway — without this, sustained burn retries a full
                # launch cycle EVERY tick (a fork/kill hot loop that
                # wedges the tick thread inside launch timeouts)
                self._last_action_t = self.clock()
                self._event("scale_up_failed", **sig)
        elif (wants_down and self._down_streak >= self.down_sustain
                and actual > self.min_replicas and cooled):
            rank = self._pick_scale_down()
            removed = None if rank is None else \
                self.fleet.remove_replica(rank, grace=self.drain_grace)
            if removed is not None:
                action = "down"
                self._target = max(self.min_replicas, actual - 1)
                self._last_action_t = self.clock()  # drain took time
                self._down_streak = 0
                self._event("scale_down", rank=rank, **sig)
            elif rank is not None:
                # the rank vanished between the pick and the remove
                # (e.g. the monitor retired it): nothing was removed,
                # so this tick is a hold, not a phantom "down" — the
                # capacity drop already happened without us
                self._event("scale_down_raced", rank=rank, **sig)
        actual_now = self.fleet.replica_count()
        self.peak_replicas = max(self.peak_replicas, actual_now)
        _metrics.inc("autoscaler.decisions", action=action)
        _metrics.set_gauge("autoscaler.replicas", self._target,
                           state="target")
        _metrics.set_gauge("autoscaler.replicas", actual_now,
                           state="actual")
        return action

    def _pick_scale_down(self):
        """The scale-down victim: a ROUTABLE replica (never one already
        draining/ejected/down — those are not carrying capacity, and a
        second drain on them would race the first), least affinity-hot
        first; ties retire the newest rank, so the longest-lived
        replica keeps its warm caches.  None when nothing is safely
        removable this tick."""
        router = self.fleet.router
        ranks = {f"r{rank}": rank for rank in self.fleet.replica_ranks()}
        candidates = [rid for rid in router.routable_ids()
                      if rid in ranks]
        if not candidates:
            return None
        counts = router.affinity_counts()
        candidates.sort(
            key=lambda rid: (counts.get(rid, 0), -ranks[rid]))
        return ranks[candidates[0]]

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle-tpu-autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # pt-lint: ok[PT005]
                # the control loop must outlive one bad pass (a replica
                # racing teardown mid-signal-read); leave evidence
                self._event("tick_error",
                            error=f"{type(e).__name__}: {e}")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval * 4))
        return True

    def describe(self):
        with self._lock:
            events = list(self.events)
        # decision state is owned by _tick_lock, not the events lock —
        # taken AFTER _lock is released, so no nesting edge
        with self._tick_lock:
            target = self._target
            peak = self.peak_replicas
        return {
            "min": self.min_replicas, "max": self.max_replicas,
            "target": target,
            "actual": self.fleet.replica_count(),
            "peak": peak,
            "burn_up": self.burn_up, "occ_up": self.occ_up,
            "occ_down": self.occ_down,
            "cooldown_s": self.cooldown_s,
            "deriv_up": self.deriv_up,
            "queue_deriv_up": self.queue_deriv_up,
            "deriv_window_s": self.deriv_window_s,
            "deriv_floor": self.deriv_floor,
            "d_occupancy": self.timeseries.derivative(
                "occupancy", self.deriv_window_s),
            "d_queue_depth": self.timeseries.derivative(
                "queue_depth", self.deriv_window_s),
            "events": events,
        }

    def _event(self, kind, **data):
        row = dict(data, kind=kind, t=time.time())
        with self._lock:
            self.events.append(row)
        try:
            from ..observability import flight as _flight

            _flight.record(f"autoscaler.{kind}", **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: scaling must
            # scale even when telemetry is broken)
