"""Continuous-batching scheduler: slots, admission, eviction.

The engine decodes at ONE fixed compiled batch shape (`max_slots`
sequence slots).  This scheduler decides, each engine step, which
sequence occupies which slot:

  * **admission** — waiting sequences enter freed slots FIFO, as soon
    as a slot AND enough pages for their prompt exist (no head-of-line
    blocking on the longest in-flight request: a finished sequence's
    slot is refilled on the very next step).
  * **completion** — a sequence that emitted eos / exhausted
    max_new_tokens (or was cancelled) releases its slot and pages at
    the next `schedule()`.
  * **eviction** — when the pool cannot cover every running sequence's
    next `chunk` tokens, the YOUNGEST running sequence (latest
    admission) is preempted back to the waiting queue's FRONT: its
    pages free immediately, and on re-admission it re-prefills from
    prompt + tokens-generated-so-far, which continues the greedy stream
    exactly (recompute-style preemption — deterministic, no KV
    snapshot).  Evicting the youngest keeps the oldest request's
    latency bound tight.
  * **prefix sharing** (ISSUE 13) — with a `PrefixIndex` attached,
    admission looks up the longest cached page-aligned prefix of the
    prompt, takes pool references on the matched pages
    (`PagePool.share`), and allocates private pages only for the tail
    — the engine then prefills only `[shared_len, s0)`.  Under page
    pressure an LRU tier of refcount-IDLE cached prefixes is reclaimed
    FIRST (`PrefixIndex.evict_idle`), sitting between FIFO admission
    and youngest-first recompute eviction: cold cache always dies
    before live work.

The clock is injectable and ordering is decided by admission sequence
numbers, never wall time — the unit tests drive the whole policy
without sleeping.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

import numpy as np

from .. import qos as _qos
from .paging import OutOfPages, PagePool, SCRATCH_PAGE

__all__ = ["Sequence", "Scheduler", "SchedulerOutput"]

_RANKS = tuple(_qos.class_rank(c) for c in _qos.CLASSES)

# sliding window over which per-tenant decode-slot-ms rates (the
# quota/fairness unit — same unit the TenantLedger bills) are averaged
_QUOTA_WINDOW_S = 10.0


def _parse_quotas(raw):
    """``class:slots`` pairs (comma-separated) → {class: float slots}.
    Malformed entries are dropped — a bad env var must not take the
    scheduler down."""
    out = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        cls, _, val = part.rpartition(":")
        cls = _qos.normalize_class(cls)
        try:
            val = float(val)
        except ValueError:
            continue
        if cls is not None and val > 0:
            out[cls] = val
    return out

WAITING, RUNNING, FINISHED, CANCELLED = (
    "waiting", "running", "finished", "cancelled")


class Sequence:
    """One request's decode state (host view)."""

    _ids = itertools.count()

    def __init__(self, input_ids, max_new_tokens, eos_token_id=None,
                 request_id=None, arrived_at=0.0, tenant_id=None,
                 priority_class=None, deadline=None,
                 prebilled_tokens=0):
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        self.prompt = ids
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.request_id = request_id or f"seq-{next(self._ids)}"
        self.tenant_id = tenant_id   # who the ledger bills (ISSUE 16)
        # what was promised (ISSUE 18): orders admission and picks
        # preemption victims; validate-or-drop to the default class
        self.priority_class = (_qos.normalize_class(priority_class)
                               or _qos.DEFAULT_CLASS)
        self.arrived_at = float(arrived_at)
        # absolute monotonic instant (scheduler clock) after which this
        # request is worthless to its client (ISSUE 20 / ROADMAP 4):
        # admission sheds an already-expired sequence instead of
        # prefilling work nobody will wait for
        self.deadline = None if deadline is None else float(deadline)
        # mid-stream failover billing (ISSUE 20): the first N accepted
        # tokens were already billed by the replica that died — the
        # resume replica re-derives them (the divergence check's verify
        # token) but must not bill them again
        self.prebilled_tokens = max(0, int(prebilled_tokens))
        self._page_mark = None       # last page-seconds charge instant
        self.timeline = None       # optional RequestTimeline (ISSUE 15)
        self.state = WAITING
        self.tokens = []           # accepted generated tokens
        self.pages = []            # live page ids (engine's pools)
        self.length = 0            # tokens materialized in the cache
        self.shared_len = 0        # cached-prefix tokens (page-aligned)
        self.shared_nodes = []     # matched PrefixIndex nodes (opaque)
        self.cache_state = None    # hit | partial | miss (at admission)
        self.slot = None
        self.last_token = None     # next decode step's input token
        self.admit_seqno = None    # ordering: eviction picks the max
        self.evictions = 0
        self.finish_reason = None
        self.handle = None         # engine-attached delivery sink

    # --- derived ------------------------------------------------------------
    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, CANCELLED)

    def resume_prompt(self) -> np.ndarray:
        """What a (re-)prefill must process: the original prompt plus
        everything already emitted — recompute preemption replays the
        stream deterministically."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def output_ids(self) -> np.ndarray:
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def __repr__(self):
        return (f"Sequence({self.request_id}, {self.state}, "
                f"len={self.length}, gen={len(self.tokens)}/"
                f"{self.max_new_tokens})")


class SchedulerOutput:
    """One schedule() decision: which sequences need a prefill this
    step, who is running, and who was preempted."""

    def __init__(self, prefills, running, evicted, finished):
        self.prefills = prefills   # newly admitted (pages allocated)
        self.running = running     # every live slot after admission
        self.evicted = evicted     # preempted back to waiting
        self.finished = finished   # released this schedule()


class Scheduler:
    def __init__(self, max_slots: int, pool: PagePool,
                 max_pages_per_seq: int, clock=time.monotonic,
                 prefix_index=None, decision_ring=None,
                 tenant_ledger=None, qos_age_s=None, quotas=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        self.pool = pool
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.clock = clock
        self.prefix_index = prefix_index  # optional PrefixIndex
        # QoS policy knobs (ISSUE 18): aging bounds starvation (a
        # waiting sequence gains one rank per qos_age_s seconds), and
        # `quotas` caps a TENANT's decode-slot rate per class —
        # {"free": 2.0} = a free tenant may hold at most ~2 decode
        # slots averaged over the quota window; over-quota tenants are
        # admitted last and evicted first WITHIN their class
        # (work-conserving: slots never idle to enforce a quota)
        if qos_age_s is None:
            qos_age_s = float(os.environ.get(
                "PADDLE_TPU_QOS_AGE_S", "") or 30.0)
        self.qos_age_s = max(0.0, float(qos_age_s))
        if quotas is None:
            quotas = _parse_quotas(os.environ.get(
                "PADDLE_TPU_QOS_QUOTAS", ""))
        self.quotas = dict(quotas or {})
        self._slot_ms = {}         # tenant -> deque[(t, slot_ms)]
        # optional timeseries.DecisionRing (ISSUE 15): every admit /
        # evict-recompute / prefix-reclaim decision lands there with
        # the page pressure AT DECISION TIME, so a request's token gap
        # can be attributed to the co-scheduled work that caused it
        self.decisions = decision_ring
        # optional TenantLedger (ISSUE 16): the scheduler owns every
        # page-residency edge (admit / grow / evict / release), so it
        # is THE place KV page-seconds — ∫ page_count dt — integrate
        self.tenant_ledger = tenant_ledger
        self._lock = threading.RLock()
        self._waiting = deque()
        self._running = {}         # slot -> Sequence
        self._seqno = itertools.count()
        self._by_id = {}           # request_id -> Sequence (live only)

    # --- intake -------------------------------------------------------------
    def submit(self, seq: Sequence) -> None:
        max_len = self.max_pages_per_seq * self.pool.page_size
        need = seq.prompt.size + seq.max_new_tokens
        if need > max_len:
            raise ValueError(
                f"prompt+max_new_tokens = {need} exceeds the engine's "
                f"max sequence length {max_len} "
                f"({self.max_pages_per_seq} pages x "
                f"{self.pool.page_size})")
        with self._lock:
            if seq.request_id in self._by_id:
                raise ValueError(
                    f"duplicate request id {seq.request_id!r}")
            seq.arrived_at = self.clock()
            self._by_id[seq.request_id] = seq
            self._waiting.append(seq)

    def cancel(self, request_id) -> bool:
        """Mark a live sequence cancelled; its slot/pages release at the
        next schedule().  Returns False for unknown/finished ids."""
        with self._lock:
            seq = self._by_id.get(request_id)
            if seq is None or seq.done:
                return False
            seq.state = CANCELLED
            seq.finish_reason = "cancelled"
            return True

    def finish(self, seq: Sequence, reason: str) -> None:
        """Called by the engine when a running sequence completes."""
        with self._lock:
            if seq.done:
                return
            seq.state = FINISHED
            seq.finish_reason = reason

    # --- the per-step decision ----------------------------------------------
    def _decide(self, kind, **data):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """One decision-ring entry, stamped with the pool pressure at
        decision time.  Guarded: the scheduler must schedule even when
        the observability plane is broken."""
        if self.decisions is None:
            return
        try:
            self.decisions.record(
                kind, pressure=round(self.pool.utilization(), 4),
                **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)

    # --- QoS accounting / ordering (ISSUE 18) -------------------------------
    def note_decode_slot_ms(self, tenant_id, ms):
        """One decode step's slot occupancy for one tenant — the engine
        feeds this alongside the ledger's `record_decode_slot_ms`, so
        quotas and fairness are priced in the SAME decode-slot-ms unit
        the tenant is billed in."""
        with self._lock:
            q = self._slot_ms.get(tenant_id)
            if q is None:
                q = self._slot_ms[tenant_id] = deque()
            now = self.clock()
            q.append((now, float(ms)))
            horizon = now - _QUOTA_WINDOW_S
            while q and q[0][0] < horizon:
                q.popleft()

    def _slot_rate_locked(self, tenant_id):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Average decode slots this tenant held over the quota window
        (1.0 = one slot continuously busy for it)."""
        q = self._slot_ms.get(tenant_id)
        if not q:
            return 0.0
        now = self.clock()
        horizon = now - _QUOTA_WINDOW_S
        while q and q[0][0] < horizon:
            q.popleft()
        return sum(ms for _, ms in q) / (_QUOTA_WINDOW_S * 1e3)

    def _over_quota_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        quota = self.quotas.get(seq.priority_class)
        if quota is None or seq.tenant_id is None:
            return False
        return self._slot_rate_locked(seq.tenant_id) > quota

    def _eff_rank_locked(self, seq, now):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Class rank after starvation aging: +1 rank per `qos_age_s`
        waited seconds, capped at the top class — a batch sequence
        eventually outranks a steady paid stream in ADMISSION order
        (preemption stays on static rank: aging earns a slot, not the
        right to take someone else's)."""
        rank = _qos.class_rank(seq.priority_class)
        if self.qos_age_s <= 0:
            return rank
        waited = max(0.0, now - seq.arrived_at)
        return min(max(_RANKS), rank + int(waited / self.qos_age_s))

    def _admission_order_locked(self, now):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Waiting sequences in admission order: highest effective rank
        first, FIFO within a rank (a preempted sequence keeps its
        original arrival, so it resumes before newer same-class work),
        under-quota tenants before over-quota ones at equal rank, and
        weighted decode-slot fairness as the final tie-break (the
        tenant with the smallest usage-per-weight goes first)."""
        def key(pair):
            idx, seq = pair
            usage = self._slot_rate_locked(seq.tenant_id) \
                / _qos.class_weight(seq.priority_class)
            return (-self._eff_rank_locked(seq, now),
                    self._over_quota_locked(seq),
                    seq.arrived_at, round(usage, 6), idx)
        return [s for _, s in
                sorted(enumerate(self._waiting), key=key)]

    def _charge_pages_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Integrate page-seconds since the last charge at the CURRENT
        page count, and restart the integration window.  Called before
        any page-count change (grow/evict/release) and once per
        schedule() for every running sequence, so occupancy accrues
        continuously instead of materializing only at terminal edges.
        Guarded: metering must never fail a scheduling decision."""
        if self.tenant_ledger is None:
            return
        try:
            now = self.clock()
            if seq._page_mark is not None and seq.pages:
                self.tenant_ledger.record_page_seconds(
                    seq.tenant_id,
                    len(seq.pages) * (now - seq._page_mark))
            seq._page_mark = now
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)

    def _release_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        self._charge_pages_locked(seq)
        seq._page_mark = None
        if seq.pages:
            self.pool.free(seq.pages)
            seq.pages = []
        if seq.slot is not None:
            self._running.pop(seq.slot, None)
            seq.slot = None
        self._by_id.pop(seq.request_id, None)

    def _pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.pool.page_size)

    def _target_pages(self, seq, tokens: int) -> int:
        """Pages a sequence needs to cover `tokens` cache positions,
        clamped to what it can EVER use: prompt + max_new_tokens (and
        the table width).  Without the total clamp, a decode_chunk
        reaching past the sequence's own finish line would demand pages
        for tokens that only ever land in the scratch page — and could
        evict (or refuse to admit) a sequence that actually fits."""
        total = seq.prompt.size + seq.max_new_tokens
        return min(self._pages_needed(min(tokens, total)),
                   self.max_pages_per_seq)

    def schedule(self, chunk: int = 1) -> SchedulerOutput:
        """One step's slot/page plan:

        1. release finished/cancelled sequences (slots + pages back),
        2. grow every running sequence's page span to cover `chunk`
           more tokens, evicting the youngest on pool pressure,
        3. admit waiting sequences FIFO into free slots while pages for
           prompt + first chunk exist.

        Admission after release in the same call: a completed sequence's
        slot serves a new request on the very next decode step."""
        with self._lock:
            finished = []
            for slot in list(self._running):
                seq = self._running[slot]
                if seq.done:
                    finished.append(seq)
                    self._release_locked(seq)
            # cancelled while still waiting: drop before admission
            drop = [s for s in self._waiting if s.done]
            for seq in drop:
                finished.append(seq)
                self._by_id.pop(seq.request_id, None)
            if drop:
                self._waiting = deque(
                    s for s in self._waiting if not s.done)

            evicted = []
            # 2. page headroom for the next `chunk` decode tokens; a
            # running seq writes positions [length, length+chunk)
            for slot in sorted(self._running):
                seq = self._running.get(slot)
                if seq is None or seq.slot is None:
                    continue  # evicted earlier in this pass
                # settle page-seconds at the OLD page count before any
                # growth this step (and once per step regardless — the
                # integral accrues continuously)
                self._charge_pages_locked(seq)
                while True:
                    target = self._target_pages(
                        seq, seq.length + max(1, int(chunk)))
                    need = target - len(seq.pages)
                    if need <= 0:
                        break
                    try:
                        seq.pages.extend(self.pool.alloc(need))
                        break
                    except OutOfPages:
                        # LRU tier first: reclaim refcount-idle cached
                        # prefixes before touching any live sequence
                        if self.prefix_index is not None:
                            got = self.prefix_index.evict_idle(need)
                            if got > 0:
                                self._decide(
                                    "prefix_reclaim", pages=got,
                                    requested=need,
                                    for_request=seq.request_id)
                                continue
                        # youngest-first preemption INCLUDING the
                        # growing sequence itself: when it is the
                        # youngest, it self-preempts rather than
                        # throwing away an older request's longer KV
                        victim = self._evict_youngest_locked()
                        if victim is None:
                            break  # nothing live to evict (can't happen
                            # while seq itself is live; belt-and-braces)
                        self._decide(
                            "evict_recompute",
                            request_id=victim.request_id,
                            for_request=seq.request_id,
                            generated=len(victim.tokens))
                        evicted.append(victim)
                        if victim is seq:
                            break

            # 3. priority-ordered admission into free slots: strict
            # priority with starvation aging, FIFO within a class
            # (ISSUE 18 — pre-QoS this was plain FIFO, which the
            # single-class case still degenerates to).  A high-class
            # candidate that cannot get a slot or pages preempts the
            # lowest-class youngest running sequence via the SAME
            # recompute-eviction path pressure uses — the victim
            # resumes warm from the prefix cache, stream intact.
            prefills = []
            while self._waiting:
                now = self.clock()
                seq = self._admission_order_locked(now)[0]
                if seq.deadline is not None and now >= seq.deadline:
                    # engine-side deadline shed (ISSUE 20 satellite /
                    # ROADMAP 4): the budget expired while queued —
                    # prefilling now only steals pages from requests
                    # someone still wants.  Honest reason, counted.
                    self._waiting.remove(seq)
                    self._by_id.pop(seq.request_id, None)
                    seq.state = FINISHED
                    seq.finish_reason = "deadline_exceeded"
                    finished.append(seq)
                    self._decide("deadline_shed",
                                 request_id=seq.request_id,
                                 waited_s=round(now - seq.arrived_at, 4),
                                 **{"class": seq.priority_class})
                    if seq.timeline is not None:
                        seq.timeline.event("deadline_shed",
                                           waited_s=round(
                                               now - seq.arrived_at, 4))
                    try:
                        from ...observability import metrics as _metrics

                        _metrics.inc("resilience.shed_requests",
                                     reason="deadline_exceeded")
                    except Exception:  # pt-lint: ok[PT005]
                        pass           # (observability fan-out guard)
                    continue
                if len(self._running) >= self.max_slots:
                    victim = self._preempt_for_locked(seq)
                    if victim is None:
                        break  # nothing this candidate outranks
                    evicted.append(victim)
                prompt = seq.resume_prompt()
                shared_pages = self._lookup_prefix_locked(seq, prompt)
                need = self._target_pages(
                    seq, prompt.size + max(1, int(chunk))) \
                    - len(shared_pages)
                starved = False
                while not self.pool.can_alloc(need):
                    # LRU tier first (cold cache dies before live
                    # work), then policy preemption of lower classes
                    if self.prefix_index is not None:
                        got = self.prefix_index.evict_idle(
                            need - self.pool.free_pages)
                        if got > 0:
                            self._decide("prefix_reclaim", pages=got,
                                         requested=need,
                                         for_request=seq.request_id)
                            continue
                    victim = self._preempt_for_locked(seq)
                    if victim is not None:
                        evicted.append(victim)
                        continue
                    starved = True
                    break
                if starved:
                    # release the just-pinned prefix refs before
                    # refusing — nothing skips the chosen head
                    if shared_pages:
                        self.pool.free(shared_pages)
                        seq.shared_len = 0
                        seq.shared_nodes = []
                        seq.cache_state = None
                    break
                self._waiting.remove(seq)
                seq.pages = shared_pages + self.pool.alloc(need)
                seq._page_mark = self.clock()  # residency starts NOW
                seq.slot = self._free_slot_locked()
                seq.state = RUNNING
                seq.admit_seqno = next(self._seqno)
                self._running[seq.slot] = seq
                prefills.append(seq)
                self._decide("admit", request_id=seq.request_id,
                             cache_state=seq.cache_state or "miss",
                             shared_tokens=int(seq.shared_len or 0),
                             pages=len(seq.pages),
                             prompt_tokens=int(prompt.size),
                             evictions=seq.evictions)
                if seq.timeline is not None:
                    seq.timeline.event(
                        "admitted", slot=seq.slot,
                        pages=len(seq.pages),
                        cache_state=seq.cache_state or "miss")

            running = [self._running[s] for s in sorted(self._running)]
            return SchedulerOutput(prefills, running, evicted, finished)

    def _lookup_prefix_locked(self, seq, prompt):  # pt-lint: ok[PT101,PT102] (schedule holds _lock)
        """Cached-prefix lookup for one admission candidate: pins the
        matched pages with `PagePool.share` IMMEDIATELY (so a following
        `evict_idle` pressure reclaim can never free what this admission
        is about to use) and records the share on the sequence.  The
        share cap leaves at least one prompt token for the tail — the
        prefill must still produce the first generated token."""
        seq.shared_len = 0
        seq.shared_nodes = []
        seq.cache_state = None
        if self.prefix_index is None:
            return []
        max_share = min((int(prompt.size) - 1) // self.pool.page_size,
                        self.max_pages_per_seq)
        if max_share <= 0:
            seq.cache_state = "miss"
            return []
        shared_tokens, pages, nodes = self.prefix_index.lookup(
            prompt, max_share)
        if not pages:
            seq.cache_state = "miss"
            return []
        pages = self.pool.share(pages)
        seq.shared_len = int(shared_tokens)
        seq.shared_nodes = nodes
        seq.cache_state = "hit" if len(pages) == max_share else "partial"
        return pages

    def _free_slot_locked(self):  # pt-lint: ok[PT102] (callers hold _lock)
        for s in range(self.max_slots):
            if s not in self._running:
                return s
        raise RuntimeError("no free slot (scheduler invariant broken)")

    def _evict_youngest_locked(self, below_rank=None):  # pt-lint: ok[PT102] (callers hold _lock)
        """Class-aware recompute-eviction victim: the LOWEST class
        first (paid dies last), over-quota tenants before on-quota
        ones within a class, youngest admission within that — the
        pre-QoS youngest-first policy, applied per class.  With
        `below_rank`, only sequences of strictly lower class are
        eligible (policy preemption must never evict a peer)."""
        cands = [s for s in self._running.values() if not s.done]
        if below_rank is not None:
            cands = [s for s in cands
                     if _qos.class_rank(s.priority_class) < below_rank]
        if not cands:
            return None
        victim = max(cands, key=lambda s: (
            -_qos.class_rank(s.priority_class),
            self._over_quota_locked(s), s.admit_seqno))
        self._evict_locked(victim)
        return victim

    def _preempt_for_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Policy preemption (ISSUE 18): evict the lowest-class
        youngest RUNNING sequence so the strictly-higher-class `seq`
        can take its slot/pages — through the exact recompute-eviction
        path pressure uses, so the victim resumes warm from the prefix
        cache and its stream continues bit-identically.  Returns the
        victim or None (nothing outranked)."""
        rank = _qos.class_rank(seq.priority_class)
        victim = self._evict_youngest_locked(below_rank=rank)
        if victim is None:
            return None
        self._decide("evict_preempt", request_id=victim.request_id,
                     for_request=seq.request_id,
                     victim_class=victim.priority_class,
                     for_class=seq.priority_class,
                     generated=len(victim.tokens))
        try:
            from ...observability import metrics as _metrics

            _metrics.inc("qos.preemptions",
                         **{"class": victim.priority_class})
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)
        return victim

    def _evict_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        self._charge_pages_locked(seq)
        seq._page_mark = None       # residency ends until re-admission
        self.pool.free(seq.pages)   # shared refs decrement; cache keeps
        seq.pages = []              # its own — re-admission re-shares
        self._running.pop(seq.slot, None)
        seq.slot = None
        seq.length = 0
        seq.shared_len = 0
        seq.shared_nodes = []
        seq.cache_state = None
        seq.last_token = None
        seq.state = WAITING
        seq.evictions += 1
        if seq.timeline is not None:
            seq.timeline.event("evicted", generated=len(seq.tokens))
        # FRONT of the queue: the preempted request resumes before
        # anything that arrived after it
        self._waiting.appendleft(seq)

    def release_finished(self) -> list:
        """Release every done running sequence NOW (slot + pages back to
        the pool) instead of waiting for the next schedule() — the
        engine calls this at the end of each step so a drained engine
        holds zero pages (the chaos scenario's leak assertion)."""
        with self._lock:
            released = []
            for slot in list(self._running):
                seq = self._running[slot]
                if seq.done:
                    released.append(seq)
                    self._release_locked(seq)
            return released

    # --- introspection ------------------------------------------------------
    @property
    def active_sequences(self) -> int:
        with self._lock:
            return len(self._running)

    @property
    def waiting_sequences(self) -> int:
        with self._lock:
            return len(self._waiting)

    def running_seqs(self) -> list:
        with self._lock:
            return [self._running[s] for s in sorted(self._running)]

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._running or self._waiting)

    def stats(self) -> dict:
        with self._lock:
            by_class = {c: {"running": 0, "waiting": 0}
                        for c in _qos.CLASSES}
            for s in self._running.values():
                by_class[s.priority_class]["running"] += 1
            for s in self._waiting:
                by_class[s.priority_class]["waiting"] += 1
            return {
                "running": len(self._running),
                "waiting": len(self._waiting),
                "max_slots": self.max_slots,
                "occupancy": len(self._running) / self.max_slots,
                "by_class": by_class,
            }
