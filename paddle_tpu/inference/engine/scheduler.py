"""Continuous-batching scheduler: slots, admission, eviction.

The engine decodes at ONE fixed compiled batch shape (`max_slots`
sequence slots).  This scheduler decides, each engine step, which
sequence occupies which slot:

  * **admission** — waiting sequences enter freed slots FIFO, as soon
    as a slot AND enough pages for their prompt exist (no head-of-line
    blocking on the longest in-flight request: a finished sequence's
    slot is refilled on the very next step).
  * **completion** — a sequence that emitted eos / exhausted
    max_new_tokens (or was cancelled) releases its slot and pages at
    the next `schedule()`.
  * **eviction** — when the pool cannot cover every running sequence's
    next `chunk` tokens, the YOUNGEST running sequence (latest
    admission) is preempted back to the waiting queue's FRONT: its
    pages free immediately, and on re-admission it re-prefills from
    prompt + tokens-generated-so-far, which continues the greedy stream
    exactly (recompute-style preemption — deterministic, no KV
    snapshot).  Evicting the youngest keeps the oldest request's
    latency bound tight.
  * **prefix sharing** (ISSUE 13) — with a `PrefixIndex` attached,
    admission looks up the longest cached page-aligned prefix of the
    prompt, takes pool references on the matched pages
    (`PagePool.share`), and allocates private pages only for the tail
    — the engine then prefills only `[shared_len, s0)`.  Under page
    pressure an LRU tier of refcount-IDLE cached prefixes is reclaimed
    FIRST (`PrefixIndex.evict_idle`), sitting between FIFO admission
    and youngest-first recompute eviction: cold cache always dies
    before live work.

The clock is injectable and ordering is decided by admission sequence
numbers, never wall time — the unit tests drive the whole policy
without sleeping.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from .paging import OutOfPages, PagePool, SCRATCH_PAGE

__all__ = ["Sequence", "Scheduler", "SchedulerOutput"]

WAITING, RUNNING, FINISHED, CANCELLED = (
    "waiting", "running", "finished", "cancelled")


class Sequence:
    """One request's decode state (host view)."""

    _ids = itertools.count()

    def __init__(self, input_ids, max_new_tokens, eos_token_id=None,
                 request_id=None, arrived_at=0.0, tenant_id=None):
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        self.prompt = ids
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.request_id = request_id or f"seq-{next(self._ids)}"
        self.tenant_id = tenant_id   # who the ledger bills (ISSUE 16)
        self.arrived_at = float(arrived_at)
        self._page_mark = None       # last page-seconds charge instant
        self.timeline = None       # optional RequestTimeline (ISSUE 15)
        self.state = WAITING
        self.tokens = []           # accepted generated tokens
        self.pages = []            # live page ids (engine's pools)
        self.length = 0            # tokens materialized in the cache
        self.shared_len = 0        # cached-prefix tokens (page-aligned)
        self.shared_nodes = []     # matched PrefixIndex nodes (opaque)
        self.cache_state = None    # hit | partial | miss (at admission)
        self.slot = None
        self.last_token = None     # next decode step's input token
        self.admit_seqno = None    # ordering: eviction picks the max
        self.evictions = 0
        self.finish_reason = None
        self.handle = None         # engine-attached delivery sink

    # --- derived ------------------------------------------------------------
    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, CANCELLED)

    def resume_prompt(self) -> np.ndarray:
        """What a (re-)prefill must process: the original prompt plus
        everything already emitted — recompute preemption replays the
        stream deterministically."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def output_ids(self) -> np.ndarray:
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def __repr__(self):
        return (f"Sequence({self.request_id}, {self.state}, "
                f"len={self.length}, gen={len(self.tokens)}/"
                f"{self.max_new_tokens})")


class SchedulerOutput:
    """One schedule() decision: which sequences need a prefill this
    step, who is running, and who was preempted."""

    def __init__(self, prefills, running, evicted, finished):
        self.prefills = prefills   # newly admitted (pages allocated)
        self.running = running     # every live slot after admission
        self.evicted = evicted     # preempted back to waiting
        self.finished = finished   # released this schedule()


class Scheduler:
    def __init__(self, max_slots: int, pool: PagePool,
                 max_pages_per_seq: int, clock=time.monotonic,
                 prefix_index=None, decision_ring=None,
                 tenant_ledger=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        self.pool = pool
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.clock = clock
        self.prefix_index = prefix_index  # optional PrefixIndex
        # optional timeseries.DecisionRing (ISSUE 15): every admit /
        # evict-recompute / prefix-reclaim decision lands there with
        # the page pressure AT DECISION TIME, so a request's token gap
        # can be attributed to the co-scheduled work that caused it
        self.decisions = decision_ring
        # optional TenantLedger (ISSUE 16): the scheduler owns every
        # page-residency edge (admit / grow / evict / release), so it
        # is THE place KV page-seconds — ∫ page_count dt — integrate
        self.tenant_ledger = tenant_ledger
        self._lock = threading.RLock()
        self._waiting = deque()
        self._running = {}         # slot -> Sequence
        self._seqno = itertools.count()
        self._by_id = {}           # request_id -> Sequence (live only)

    # --- intake -------------------------------------------------------------
    def submit(self, seq: Sequence) -> None:
        max_len = self.max_pages_per_seq * self.pool.page_size
        need = seq.prompt.size + seq.max_new_tokens
        if need > max_len:
            raise ValueError(
                f"prompt+max_new_tokens = {need} exceeds the engine's "
                f"max sequence length {max_len} "
                f"({self.max_pages_per_seq} pages x "
                f"{self.pool.page_size})")
        with self._lock:
            if seq.request_id in self._by_id:
                raise ValueError(
                    f"duplicate request id {seq.request_id!r}")
            seq.arrived_at = self.clock()
            self._by_id[seq.request_id] = seq
            self._waiting.append(seq)

    def cancel(self, request_id) -> bool:
        """Mark a live sequence cancelled; its slot/pages release at the
        next schedule().  Returns False for unknown/finished ids."""
        with self._lock:
            seq = self._by_id.get(request_id)
            if seq is None or seq.done:
                return False
            seq.state = CANCELLED
            seq.finish_reason = "cancelled"
            return True

    def finish(self, seq: Sequence, reason: str) -> None:
        """Called by the engine when a running sequence completes."""
        with self._lock:
            if seq.done:
                return
            seq.state = FINISHED
            seq.finish_reason = reason

    # --- the per-step decision ----------------------------------------------
    def _decide(self, kind, **data):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """One decision-ring entry, stamped with the pool pressure at
        decision time.  Guarded: the scheduler must schedule even when
        the observability plane is broken."""
        if self.decisions is None:
            return
        try:
            self.decisions.record(
                kind, pressure=round(self.pool.utilization(), 4),
                **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)

    def _charge_pages_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """Integrate page-seconds since the last charge at the CURRENT
        page count, and restart the integration window.  Called before
        any page-count change (grow/evict/release) and once per
        schedule() for every running sequence, so occupancy accrues
        continuously instead of materializing only at terminal edges.
        Guarded: metering must never fail a scheduling decision."""
        if self.tenant_ledger is None:
            return
        try:
            now = self.clock()
            if seq._page_mark is not None and seq.pages:
                self.tenant_ledger.record_page_seconds(
                    seq.tenant_id,
                    len(seq.pages) * (now - seq._page_mark))
            seq._page_mark = now
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)

    def _release_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        self._charge_pages_locked(seq)
        seq._page_mark = None
        if seq.pages:
            self.pool.free(seq.pages)
            seq.pages = []
        if seq.slot is not None:
            self._running.pop(seq.slot, None)
            seq.slot = None
        self._by_id.pop(seq.request_id, None)

    def _pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.pool.page_size)

    def _target_pages(self, seq, tokens: int) -> int:
        """Pages a sequence needs to cover `tokens` cache positions,
        clamped to what it can EVER use: prompt + max_new_tokens (and
        the table width).  Without the total clamp, a decode_chunk
        reaching past the sequence's own finish line would demand pages
        for tokens that only ever land in the scratch page — and could
        evict (or refuse to admit) a sequence that actually fits."""
        total = seq.prompt.size + seq.max_new_tokens
        return min(self._pages_needed(min(tokens, total)),
                   self.max_pages_per_seq)

    def schedule(self, chunk: int = 1) -> SchedulerOutput:
        """One step's slot/page plan:

        1. release finished/cancelled sequences (slots + pages back),
        2. grow every running sequence's page span to cover `chunk`
           more tokens, evicting the youngest on pool pressure,
        3. admit waiting sequences FIFO into free slots while pages for
           prompt + first chunk exist.

        Admission after release in the same call: a completed sequence's
        slot serves a new request on the very next decode step."""
        with self._lock:
            finished = []
            for slot in list(self._running):
                seq = self._running[slot]
                if seq.done:
                    finished.append(seq)
                    self._release_locked(seq)
            # cancelled while still waiting: drop before admission
            drop = [s for s in self._waiting if s.done]
            for seq in drop:
                finished.append(seq)
                self._by_id.pop(seq.request_id, None)
            if drop:
                self._waiting = deque(
                    s for s in self._waiting if not s.done)

            evicted = []
            # 2. page headroom for the next `chunk` decode tokens; a
            # running seq writes positions [length, length+chunk)
            for slot in sorted(self._running):
                seq = self._running.get(slot)
                if seq is None or seq.slot is None:
                    continue  # evicted earlier in this pass
                # settle page-seconds at the OLD page count before any
                # growth this step (and once per step regardless — the
                # integral accrues continuously)
                self._charge_pages_locked(seq)
                while True:
                    target = self._target_pages(
                        seq, seq.length + max(1, int(chunk)))
                    need = target - len(seq.pages)
                    if need <= 0:
                        break
                    try:
                        seq.pages.extend(self.pool.alloc(need))
                        break
                    except OutOfPages:
                        # LRU tier first: reclaim refcount-idle cached
                        # prefixes before touching any live sequence
                        if self.prefix_index is not None:
                            got = self.prefix_index.evict_idle(need)
                            if got > 0:
                                self._decide(
                                    "prefix_reclaim", pages=got,
                                    requested=need,
                                    for_request=seq.request_id)
                                continue
                        # youngest-first preemption INCLUDING the
                        # growing sequence itself: when it is the
                        # youngest, it self-preempts rather than
                        # throwing away an older request's longer KV
                        victim = self._evict_youngest_locked()
                        if victim is None:
                            break  # nothing live to evict (can't happen
                            # while seq itself is live; belt-and-braces)
                        self._decide(
                            "evict_recompute",
                            request_id=victim.request_id,
                            for_request=seq.request_id,
                            generated=len(victim.tokens))
                        evicted.append(victim)
                        if victim is seq:
                            break

            # 3. FIFO admission into free slots
            prefills = []
            while self._waiting and len(self._running) < self.max_slots:
                seq = self._waiting[0]
                prompt = seq.resume_prompt()
                shared_pages = self._lookup_prefix_locked(seq, prompt)
                need = self._target_pages(
                    seq, prompt.size + max(1, int(chunk))) \
                    - len(shared_pages)
                if not self.pool.can_alloc(need):
                    got = 0
                    if self.prefix_index is not None:
                        got = self.prefix_index.evict_idle(
                            need - self.pool.free_pages)
                        if got > 0:
                            self._decide("prefix_reclaim", pages=got,
                                         requested=need,
                                         for_request=seq.request_id)
                    if got == 0 or not self.pool.can_alloc(need):
                        # release the just-pinned prefix refs before
                        # refusing — strict FIFO: nothing skips the head
                        if shared_pages:
                            self.pool.free(shared_pages)
                            seq.shared_len = 0
                            seq.shared_nodes = []
                            seq.cache_state = None
                        break
                self._waiting.popleft()
                seq.pages = shared_pages + self.pool.alloc(need)
                seq._page_mark = self.clock()  # residency starts NOW
                seq.slot = self._free_slot_locked()
                seq.state = RUNNING
                seq.admit_seqno = next(self._seqno)
                self._running[seq.slot] = seq
                prefills.append(seq)
                self._decide("admit", request_id=seq.request_id,
                             cache_state=seq.cache_state or "miss",
                             shared_tokens=int(seq.shared_len or 0),
                             pages=len(seq.pages),
                             prompt_tokens=int(prompt.size),
                             evictions=seq.evictions)
                if seq.timeline is not None:
                    seq.timeline.event(
                        "admitted", slot=seq.slot,
                        pages=len(seq.pages),
                        cache_state=seq.cache_state or "miss")

            running = [self._running[s] for s in sorted(self._running)]
            return SchedulerOutput(prefills, running, evicted, finished)

    def _lookup_prefix_locked(self, seq, prompt):  # pt-lint: ok[PT101,PT102] (schedule holds _lock)
        """Cached-prefix lookup for one admission candidate: pins the
        matched pages with `PagePool.share` IMMEDIATELY (so a following
        `evict_idle` pressure reclaim can never free what this admission
        is about to use) and records the share on the sequence.  The
        share cap leaves at least one prompt token for the tail — the
        prefill must still produce the first generated token."""
        seq.shared_len = 0
        seq.shared_nodes = []
        seq.cache_state = None
        if self.prefix_index is None:
            return []
        max_share = min((int(prompt.size) - 1) // self.pool.page_size,
                        self.max_pages_per_seq)
        if max_share <= 0:
            seq.cache_state = "miss"
            return []
        shared_tokens, pages, nodes = self.prefix_index.lookup(
            prompt, max_share)
        if not pages:
            seq.cache_state = "miss"
            return []
        pages = self.pool.share(pages)
        seq.shared_len = int(shared_tokens)
        seq.shared_nodes = nodes
        seq.cache_state = "hit" if len(pages) == max_share else "partial"
        return pages

    def _free_slot_locked(self):  # pt-lint: ok[PT102] (callers hold _lock)
        for s in range(self.max_slots):
            if s not in self._running:
                return s
        raise RuntimeError("no free slot (scheduler invariant broken)")

    def _evict_youngest_locked(self):  # pt-lint: ok[PT102] (callers hold _lock)
        cands = [s for s in self._running.values() if not s.done]
        if not cands:
            return None
        victim = max(cands, key=lambda s: s.admit_seqno)
        self._evict_locked(victim)
        return victim

    def _evict_locked(self, seq):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        self._charge_pages_locked(seq)
        seq._page_mark = None       # residency ends until re-admission
        self.pool.free(seq.pages)   # shared refs decrement; cache keeps
        seq.pages = []              # its own — re-admission re-shares
        self._running.pop(seq.slot, None)
        seq.slot = None
        seq.length = 0
        seq.shared_len = 0
        seq.shared_nodes = []
        seq.cache_state = None
        seq.last_token = None
        seq.state = WAITING
        seq.evictions += 1
        if seq.timeline is not None:
            seq.timeline.event("evicted", generated=len(seq.tokens))
        # FRONT of the queue: the preempted request resumes before
        # anything that arrived after it
        self._waiting.appendleft(seq)

    def release_finished(self) -> list:
        """Release every done running sequence NOW (slot + pages back to
        the pool) instead of waiting for the next schedule() — the
        engine calls this at the end of each step so a drained engine
        holds zero pages (the chaos scenario's leak assertion)."""
        with self._lock:
            released = []
            for slot in list(self._running):
                seq = self._running[slot]
                if seq.done:
                    released.append(seq)
                    self._release_locked(seq)
            return released

    # --- introspection ------------------------------------------------------
    @property
    def active_sequences(self) -> int:
        with self._lock:
            return len(self._running)

    @property
    def waiting_sequences(self) -> int:
        with self._lock:
            return len(self._waiting)

    def running_seqs(self) -> list:
        with self._lock:
            return [self._running[s] for s in sorted(self._running)]

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._running or self._waiting)

    def stats(self) -> dict:
        with self._lock:
            return {
                "running": len(self._running),
                "waiting": len(self._waiting),
                "max_slots": self.max_slots,
                "occupancy": len(self._running) / self.max_slots,
            }
