"""Block-pool paged KV allocation (host side).

The engine's KV state lives in fixed-size pages drawn from one shared
pool per layer (`[num_pages, kv_heads, page_size, head_dim]` device
arrays owned by the engine).  This module is the HOST allocator over
those pools: which page ids are free, which are live, and how
fragmented the pool is.  It never touches device memory — the engine
applies `defrag()` moves to the device arrays and the per-sequence
page tables.

Page 0 is RESERVED as the scratch page: free batch slots point their
whole page-table row at it, masked/dead writes land in it, and it is
never allocated to a sequence — so a stale table entry can corrupt at
worst the page nobody reads.

Prefix caching (ISSUE 13): pages carry REFCOUNTS.  `alloc()` grants a
page at refcount 1; `share()` lets a second holder (another sequence,
or the scheduler's radix prefix index) take a reference to the same
physical page, and `free()` only returns a page to the free list when
its last reference drops.  Full committed-prefix pages are immutable
by contract — the engine never writes a page whose content is shared
(copy-on-write happens at the boundary: the partial tail page is
always a private fresh allocation) — so two page tables pointing at
one physical page is safe for the kernel by construction.  The int8
KV tier's scale tables ride next to the pools indexed by the same page
ids, so sharing a page shares its scale rows under the same refcount.
`stats()` reports the physical/logical split (`shared_pages`,
`logical_pages`) so `engine.page_utilization` counts a shared page
ONCE — capacity scales with unique tokens, and the telemetry says so.
"""
from __future__ import annotations

import threading

__all__ = ["PagePool", "OutOfPages", "SCRATCH_PAGE"]

SCRATCH_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool cannot satisfy an allocation — the scheduler's cue to
    evict (or stop admitting) rather than a request failure."""


class PagePool:
    """Free-list allocator over ``num_pages`` fixed-size pages.

    Thread-safe (the engine loop allocates while handler threads
    submit).  Telemetry: `stats()` feeds the `engine.page_utilization`
    gauge; every alloc/free keeps an exact live count so a leak shows
    up as a non-zero `used_pages` after drain — the chaos scenario's
    first assertion.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (page 0 is the "
                             "reserved scratch page)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # pop() yields ascending ids (1, 2, ...): fresh pools fill from
        # the bottom, which keeps the untouched tail contiguous
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._live = set()
        self._refs = {}            # page -> refcount (live pages only)
        self._peak = 0

    # --- allocation ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable pages (scratch page excluded)."""
        return self.num_pages - 1

    @property
    def used_pages(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def can_alloc(self, n: int) -> bool:
        with self._lock:
            return len(self._free) >= n

    def alloc(self, n: int) -> list:
        """n page ids, or raise `OutOfPages` (allocation is all-or-
        nothing: a partial grant would leak on the error path)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if len(self._free) < n:
                raise OutOfPages(
                    f"need {n} page(s), {len(self._free)} free of "
                    f"{self.capacity}")
            pages = [self._free.pop() for _ in range(n)]
            self._live.update(pages)
            for p in pages:
                self._refs[p] = 1
            self._peak = max(self._peak, len(self._live))
        return pages

    def share(self, pages) -> list:
        """Take one MORE reference on each live page (prefix-cache page
        sharing): the page now has two holders, and `free()` from either
        leaves it live for the other.  Sharing a dead or scratch page is
        loud — handing out a reference to a page the free list could
        re-grant would alias two sequences onto one page.  Returns the
        pages (int-normalized) for chaining into a page-table list."""
        out = []
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRATCH_PAGE:
                    raise ValueError("cannot share the scratch page")
                if p not in self._live:
                    raise ValueError(f"share of dead page {p}")
                self._refs[p] += 1
                out.append(p)
        return out

    def free(self, pages) -> None:
        """Drop one reference per page; a page returns to the pool when
        its LAST reference drops.  Over-frees (a page freed more times
        than it was alloc'd+shared) and scratch-page frees are errors —
        both mean the caller's bookkeeping is corrupt, and silently
        absorbing them would hand one page to two sequences later."""
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRATCH_PAGE:
                    raise ValueError("cannot free the scratch page")
                if p not in self._live:
                    raise ValueError(f"double free of page {p}")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._live.discard(p)
                    self._free.append(p)

    def refcount(self, page) -> int:
        """Live reference count for one page (0 when free/dead)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def ref_counts(self) -> dict:
        """Snapshot of every live page's refcount — the chaos leak
        assertion ("zero refcount leak") diffs this against empty."""
        with self._lock:
            return dict(self._refs)

    # --- defrag -------------------------------------------------------------
    def defrag(self) -> dict:
        """Compact live pages into the densest prefix {1..used}.

        Returns ``{src: dst}`` moves (empty when already compact).  The
        caller must apply each move to the device pools (copy page src
        -> dst) and rewrite every page table BEFORE the next decode
        step.  Compaction keeps the pool's touched high-water mark (and
        therefore the working set a future pool resize / snapshot must
        carry) at the live minimum."""
        with self._lock:
            live = sorted(self._live)
            moves = {}
            dst = 1
            for src in live:
                if src != dst:
                    moves[src] = dst
                dst += 1
            if moves:
                n = len(live)
                self._live = set(range(1, n + 1))
                self._free = list(range(self.num_pages - 1, n, -1))
                # refcounts travel with the page: a SHARED page moves
                # exactly once (one physical copy), and every holder's
                # table is rewritten to the same destination
                self._refs = {moves.get(p, p): r
                              for p, r in self._refs.items()}
        return moves

    # --- telemetry ----------------------------------------------------------
    def utilization(self) -> float:
        with self._lock:
            return len(self._live) / max(1, self.capacity)

    def stats(self) -> dict:
        with self._lock:
            shared = sum(1 for r in self._refs.values() if r > 1)
            logical = sum(self._refs.values())
            return {
                "page_size": self.page_size,
                "num_pages": self.num_pages,
                "capacity": self.capacity,
                # `used` counts each physical page ONCE regardless of
                # how many holders reference it (the ISSUE 13 satellite
                # fix: sharing must not inflate utilization/peak); the
                # shared/logical split makes the dedup visible — saved
                # pages = logical_pages - used
                "used": len(self._live),
                "free": len(self._free),
                "shared_pages": shared,
                "logical_pages": logical,
                "peak_used": self._peak,
                "utilization": len(self._live) / max(1, self.capacity),
            }
