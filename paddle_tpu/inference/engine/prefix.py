"""Radix prefix index: page-aligned prompt prefixes -> committed pages.

At serving scale most prompts share prefixes (system prompts, few-shot
templates, multi-turn history).  The page table already admits sharing
— the ragged paged-attention kernel gathers pages per-sequence through
the table, so two sequences pointing at one physical page costs
nothing — and `PagePool` refcounts make the lifetime safe.  What is
missing is the LOOKUP: given a new prompt, which already-committed
pages hold its longest page-aligned prefix?

This index is a radix tree with ONE node per page: a node's key is the
page's exact `page_size`-token content, its value the physical page id
(the index holds its own pool reference on it, taken with
`PagePool.share`).  Matching is by real token values — a poisoned or
stale routing fingerprint can therefore never produce a wrong-token
stream, only a miss.  Committed pages are immutable by construction
(the engine only ever commits FULL prompt pages; the partial tail page
stays private to its sequence), so a cached page's content is a pure
function of the token path that reaches it.

Eviction is LRU over idle leaves: a leaf whose page refcount is 1
(the index is the only holder) frees immediately; leaves referenced by
live sequences are skipped for pool-pressure reclaims.  Removing a
leaf may expose its parent as the next candidate, so deep cold chains
unwind back-to-front.  `max_tokens` bounds the cache (insert reclaims
LRU idle leaves past it); the scheduler calls `evict_idle` as the
reclaim tier BETWEEN FIFO admission and youngest-first recompute
eviction, so idle cache always dies before a live sequence does.

Quantized-KV sidecar: under ``kv_precision='int8'`` the pools hold
int8 + scales, but a warm tail-prefill must attend the prefix at the
SAME precision a cold prefill would (full), or warm and cold streams
diverge beyond reduction-order noise.  Nodes therefore carry an
optional per-layer exact (k, v) page copy captured at commit time
(from the cold prefill's dense buffers, before quantized pack); the
exact tier needs none — the pools themselves are exact.
"""
from __future__ import annotations

import threading
import time

from .paging import PagePool

__all__ = ["PrefixIndex"]


class _Node:
    __slots__ = ("key", "page", "children", "parent", "last_used",
                 "exact")

    def __init__(self, key, page, parent, now, exact=None):
        self.key = key             # tuple of page_size token ids
        self.page = int(page)      # physical page id (index holds a ref)
        self.children = {}         # key tuple -> _Node
        self.parent = parent       # _Node or None (root child)
        self.last_used = now
        self.exact = exact         # optional per-layer (k, v) page copy


class PrefixIndex:
    """Thread-safe; lock order is scheduler -> index -> pool (the index
    never calls back into the scheduler)."""

    def __init__(self, pool: PagePool, max_tokens: int = 0,
                 clock=time.monotonic, on_evict=None):
        self.pool = pool
        self.page_size = int(pool.page_size)
        # 0 = unbounded by tokens (pool pressure still reclaims)
        self.max_tokens = max(0, int(max_tokens))
        self.clock = clock
        self.on_evict = on_evict   # callable(n_pages) -> None
        self._lock = threading.RLock()
        self._children = {}        # root level: key tuple -> _Node
        self._nodes = 0
        self._evicted_pages = 0

    # --- helpers ------------------------------------------------------------
    def _chunks(self, tokens, max_pages):
        ps = self.page_size
        n = min(len(tokens) // ps, max_pages)
        return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                for i in range(n)]

    # --- lookup -------------------------------------------------------------
    def lookup(self, tokens, max_pages: int):
        """Longest cached page-aligned prefix of `tokens`, capped at
        `max_pages` pages (the caller caps so at least one prompt token
        is always left for the tail prefill).  Returns
        ``(shared_tokens, pages, nodes)`` — pages are NOT yet shared
        into the pool; the caller takes its references via
        `PagePool.share` only when it actually admits the sequence.
        Touches `last_used` along the matched path (LRU)."""
        now = self.clock()
        pages, nodes = [], []
        with self._lock:
            level = self._children
            for key in self._chunks(tokens, max_pages):
                node = level.get(key)
                if node is None:
                    break
                node.last_used = now
                pages.append(node.page)
                nodes.append(node)
                level = node.children
        return len(pages) * self.page_size, pages, nodes

    # --- insert -------------------------------------------------------------
    def insert(self, tokens, pages, exact=None) -> int:
        """Commit the full-page prefix of `tokens` backed by `pages`
        (the owning sequence's first ``len(tokens)//page_size`` pages).
        Chunks already present keep the CACHE's canonical page (the
        sequence keeps its private copy — identical content); new
        chunks take a pool reference on the sequence's page.  `exact`
        (optional, int8-KV tier): per-page per-layer exact (k, v)
        copies aligned with `pages`.  Returns the number of NEW pages
        the cache now holds."""
        now = self.clock()
        added = 0
        with self._lock:
            level = self._children
            parent = None
            chunks = self._chunks(tokens, len(pages))
            for i, key in enumerate(chunks):
                node = level.get(key)
                if node is None:
                    page = self.pool.share([pages[i]])[0]
                    node = _Node(key, page, parent, now,
                                 exact=None if exact is None
                                 else exact[i])
                    level[key] = node
                    self._nodes += 1
                    added += 1
                else:
                    node.last_used = now
                parent = node
                level = node.children
            if self.max_tokens:
                over = self._nodes * self.page_size - self.max_tokens
                if over > 0:
                    # the bound reclaims ANY idle leaf, including ones
                    # just inserted (newest-first paths survive via LRU
                    # stamps from this very call)
                    self._evict_idle_locked(
                        -(-over // self.page_size))
        return added

    # --- eviction -----------------------------------------------------------
    def _iter_leaves_locked(self):
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node

    def _remove_leaf_locked(self, node):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        level = (self._children if node.parent is None
                 else node.parent.children)
        level.pop(node.key, None)
        self._nodes -= 1
        self.pool.free([node.page])
        self._evicted_pages += 1

    def _evict_idle_locked(self, want_pages: int) -> int:
        # one tree walk builds the idle-leaf heap; evicting a leaf may
        # expose its parent, which joins the heap if idle — O(leaves)
        # + O(log n) per eviction, not a full rescan per page (a large
        # cache reclaim runs under the scheduler's lock)
        import heapq

        heap = [(n.last_used, id(n), n)
                for n in self._iter_leaves_locked()
                if self.pool.refcount(n.page) == 1]
        heapq.heapify(heap)
        freed = 0
        while freed < want_pages and heap:
            _, _, node = heapq.heappop(heap)
            parent = node.parent
            self._remove_leaf_locked(node)
            freed += 1
            if parent is not None and not parent.children \
                    and self.pool.refcount(parent.page) == 1:
                heapq.heappush(
                    heap, (parent.last_used, id(parent), parent))
        if freed and self.on_evict is not None:
            try:
                self.on_evict(freed)
            except Exception:  # pt-lint: ok[PT005]
                pass           # (telemetry fan-out guard: eviction must
                # reclaim pages even when the metrics hook is broken)
        return freed

    def evict_idle(self, want_pages: int = 1) -> int:
        """Reclaim up to `want_pages` refcount-idle cached pages, LRU
        leaves first.  Returns how many pages actually went back to the
        free list — 0 when every cached page is also held by a live
        sequence (nothing reclaimable without hurting live work)."""
        with self._lock:
            return self._evict_idle_locked(max(1, int(want_pages)))

    def clear(self) -> int:
        """Drop EVERY cache reference (regardless of sharing) — used by
        `engine.clear_prefix_cache()` and the chaos drain assertion.
        Pages shared with live sequences stay live under the sequences'
        own references."""
        with self._lock:
            n = 0
            for node in list(self._iter_leaves_locked()):
                # unwind leaf-first so parents become leaves in turn
                while node is not None and not node.children:
                    parent = node.parent
                    self._remove_leaf_locked(node)
                    n += 1
                    node = parent
            return n

    def apply_moves(self, moves: dict) -> None:
        """Rewrite node page ids after a `PagePool.defrag()` — the
        pool remaps its refcounts, the engine remaps live page tables,
        and the index remaps here: one physical copy per page, every
        holder repointed (a shared page moves exactly once)."""
        if not moves:
            return
        with self._lock:
            stack = list(self._children.values())
            while stack:
                node = stack.pop()
                node.page = moves.get(node.page, node.page)
                stack.extend(node.children.values())

    # --- introspection ------------------------------------------------------
    @property
    def nodes(self) -> int:
        with self._lock:
            return self._nodes

    @property
    def cached_tokens(self) -> int:
        with self._lock:
            return self._nodes * self.page_size

    def stats(self) -> dict:
        with self._lock:
            return {
                "nodes": self._nodes,
                "cached_tokens": self._nodes * self.page_size,
                "max_tokens": self.max_tokens,
                "evicted_pages": self._evicted_pages,
            }
