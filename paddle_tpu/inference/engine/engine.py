"""Continuous-batching inference engine over a paged KV cache.

The serving-side answer to ROADMAP item 1: instead of one predictor
lock serving whole `generate()` calls back-to-back, the engine keeps a
FIXED compiled batch of sequence slots and advances every in-flight
sequence one (or `decode_chunk`) token(s) per step, admitting new
sequences into freed slots between steps — throughput scales with
batch occupancy, latency with queue position, and no request waits for
the longest one to finish.

Three compiled programs (per shape signature, cached):

  * **prefill** (one sequence, prompt left-padded to a bucket): the
    dense static-cache path the model families already compile —
    returns the first generated token and the dense K/V it produced.
  * **pack**: scatters the fresh dense K/V into the sequence's
    allocated pages (pools donated — in-place on TPU).
  * **decode** (the hot step): `decode_chunk` scanned steps at the
    fixed `[max_slots]` batch — each step writes every slot's current
    token into its page at `page_table[slot, len//ps], len%ps` and
    attends through `ops/pallas/paged_attention` with per-slot ragged
    lengths.  Pools donated; tokens stay on device across the scan.

Free slots ride along pointing at the reserved scratch page with
length 0: their output is discarded on the host, and the compiled
shape never changes as sequences come and go.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_ENGINE_PAGE_SIZE       tokens per KV page        (16)
  PADDLE_TPU_ENGINE_MAX_PAGES      pool size incl. scratch    (derived)
  PADDLE_TPU_ENGINE_MAX_SLOTS      compiled batch slots       (4)
  PADDLE_TPU_ENGINE_DECODE_CHUNK   decode steps per dispatch  (1)
  PADDLE_TPU_ENGINE_PREFILL_BUCKET prompt padding granule     (16)
  PADDLE_TPU_ENGINE_MAX_SEQ_LEN    per-sequence token cap     (model's)

Observability: `engine.schedule/prefill/decode/detokenize` spans on
the request-trace timeline, `engine.*` gauges (active/waiting
sequences, page utilization, batch occupancy) and counters
(`engine.sequences{event}`, `engine.tokens`) in the attach() schema.
"""
from __future__ import annotations

import functools
import queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ...observability import metrics as _metrics
from ...observability import trace as _trace
from ...resilience.overload import _env_num
from .paging import PagePool
from .scheduler import Scheduler, Sequence

__all__ = ["EngineConfig", "InferenceEngine", "RequestHandle"]


class EngineConfig:
    """Engine sizing knobs; every ctor arg falls back to its
    PADDLE_TPU_ENGINE_* env, then the default."""

    def __init__(self, page_size=None, num_pages=None, max_slots=None,
                 decode_chunk=None, prefill_bucket=None,
                 max_seq_len=None):
        self.page_size = int(page_size if page_size is not None else
                             _env_num("PADDLE_TPU_ENGINE_PAGE_SIZE", 16,
                                      int))
        self.max_slots = int(max_slots if max_slots is not None else
                             _env_num("PADDLE_TPU_ENGINE_MAX_SLOTS", 4,
                                      int))
        self.decode_chunk = int(
            decode_chunk if decode_chunk is not None else
            _env_num("PADDLE_TPU_ENGINE_DECODE_CHUNK", 1, int))
        self.prefill_bucket = int(
            prefill_bucket if prefill_bucket is not None else
            _env_num("PADDLE_TPU_ENGINE_PREFILL_BUCKET", 16, int))
        # 0 = resolve from the model's max_seq_len at engine build
        self.max_seq_len = int(
            max_seq_len if max_seq_len is not None else
            _env_num("PADDLE_TPU_ENGINE_MAX_SEQ_LEN", 0, int))
        # 0 = derived: every slot can hold a max-length sequence
        self.num_pages = int(num_pages if num_pages is not None else
                             _env_num("PADDLE_TPU_ENGINE_MAX_PAGES", 0,
                                      int))
        for name in ("page_size", "max_slots", "decode_chunk",
                     "prefill_bucket"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got "
                                 f"{getattr(self, name)}")


class RequestHandle:
    """One submitted request's delivery side: a token stream plus a
    completion event.  Tokens arrive as the engine accepts them;
    `result()` blocks for the full prompt+generated ids."""

    def __init__(self, seq: Sequence):
        self._seq = seq
        self.request_id = seq.request_id
        self._q = queue.Queue()
        self.done = threading.Event()
        self.finish_reason = None

    def _push(self, tok: int) -> None:
        self._q.put(int(tok))

    def _finish(self, reason: str) -> None:
        if self.done.is_set():
            return
        self.finish_reason = reason
        self.done.set()
        self._q.put(None)          # stream sentinel

    # --- consumer side ------------------------------------------------------
    def stream(self, timeout: float = 120.0):
        """Yield generated tokens as they land; returns at completion."""
        while True:
            tok = self._q.get(timeout=timeout)
            if tok is None:
                return
            yield tok

    def result(self, timeout: float = 120.0) -> np.ndarray:
        """Blocking: full int32 [s0 + n_generated] ids (prompt
        included, like `GenerationMixin.generate`)."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        return self._seq.output_ids()

    @property
    def tokens(self) -> list:
        return list(self._seq.tokens)

    @property
    def cancelled(self) -> bool:
        return self.finish_reason == "cancelled"


class InferenceEngine:
    """Continuous-batching engine over one `GenerationMixin` model
    (greedy decoding — the deterministic serving mode; sampling rides
    ROADMAP item 4)."""

    def __init__(self, model, config: EngineConfig = None,
                 clock=time.monotonic):
        import copy

        # own copy: max_seq_len/num_pages resolve against THIS model
        # below, and mutating the caller's object would poison a config
        # reused for a second engine over a different model
        self.config = copy.copy(config) if config is not None \
            else EngineConfig()
        self._model = model
        model.eval()
        self._params, self._buffers = model.functional_state()
        cfg = self.config
        # shape probe: one layer's dense cache tells us layers/heads/dim
        probe = model.init_kv_caches(1, 1)
        self._layers = len(probe)
        _, self._hkv, _, self._hd = probe[0][0].shape
        self._dtype = probe[0][0].dtype
        del probe
        if cfg.max_seq_len <= 0:
            cfg.max_seq_len = int(getattr(model.cfg, "max_seq_len", 0)) \
                or 2048
        self.max_pages_per_seq = -(-cfg.max_seq_len // cfg.page_size)
        if cfg.num_pages <= 0:
            cfg.num_pages = cfg.max_slots * self.max_pages_per_seq + 1
        self.pool = PagePool(cfg.num_pages, cfg.page_size)
        self.scheduler = Scheduler(cfg.max_slots, self.pool,
                                   self.max_pages_per_seq, clock=clock)
        shape = (cfg.num_pages, self._hkv, cfg.page_size, self._hd)
        self._k_pools = [jnp.zeros(shape, self._dtype)
                         for _ in range(self._layers)]
        self._v_pools = [jnp.zeros(shape, self._dtype)
                         for _ in range(self._layers)]
        self._programs = {}
        self._handles = {}         # request_id -> RequestHandle
        self._lock = threading.RLock()
        self._work = threading.Condition()
        self._thread = None
        self._running = False
        self.steps = 0

    # --- model invocation (raw jax values; paged or dense caches) -----------
    def _run_model(self, params, buffers, ids, caches, pos, start):
        from ...core import flags
        from ...core.tensor import Tensor

        with flags.no_grad_guard(), flags.trace_guard():
            with self._model.bind_state(params, buffers):
                logits, new = self._model(
                    Tensor(ids),
                    kv_caches=[tuple(Tensor(x) for x in c)
                               for c in caches],
                    cache_pos=Tensor(pos),
                    attn_start=None if start is None else Tensor(start))
        return logits._value, [tuple(x._value for x in c) for c in new]

    # --- compiled programs --------------------------------------------------
    def _prefill_program(self, sb: int):
        """One left-padded sequence at bucket length sb: greedy first
        token + the dense K/V (capacity sb+page_size so the pack
        program's last page slice never clamps)."""
        key = ("prefill", sb)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        run = self._run_model
        layers, hkv, d = self._layers, self._hkv, self._hd
        cap = sb + self.config.page_size
        dtype = self._dtype

        @jax.jit
        def prefill(params, buffers, ids, start):
            caches = [(jnp.zeros((1, hkv, cap, d), dtype),
                       jnp.zeros((1, hkv, cap, d), dtype))
                      for _ in range(layers)]
            logits, new = run(params, buffers, ids, caches,
                              jnp.zeros((), jnp.int32), start)
            tok = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return tok, [c[0] for c in new], [c[1] for c in new]

        self._programs[key] = prefill
        return prefill

    def _pack_program(self, sb: int):
        """Scatter a prefill's dense K/V (real tokens at
        [start, start+s0)) into the sequence's pages.  Pages beyond the
        prompt's span point at the scratch page — their writes are
        discarded by construction."""
        key = ("pack", sb)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        ps = self.config.page_size
        hkv, d = self._hkv, self._hd
        npb = -(-sb // ps)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def pack(k_pools, v_pools, kbufs, vbufs, pages, start):
            def put(pool, buf):
                def body(i, pool):
                    chunk = jax.lax.dynamic_slice(
                        buf, (0, 0, start + i * ps, 0), (1, hkv, ps, d))
                    return jax.lax.dynamic_update_slice(
                        pool, chunk, (pages[i], 0, 0, 0))
                return jax.lax.fori_loop(0, npb, body, pool)

            k_pools = [put(p, b) for p, b in zip(k_pools, kbufs)]
            v_pools = [put(p, b) for p, b in zip(v_pools, vbufs)]
            return k_pools, v_pools

        self._programs[key] = pack
        return pack

    def _decode_program(self, n: int):
        """`n` ragged decode steps at the fixed [max_slots] batch inside
        one compiled scan.  Pools donated: each step writes one page
        slot per sequence per layer, and donation lets XLA update in
        place instead of copying the whole pool per token."""
        key = ("decode", n)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        run = self._run_model

        @functools.partial(jax.jit, donate_argnums=(2, 3))
        def decode(params, buffers, k_pools, v_pools, tok, pt, lengths):
            def body(carry, _):
                tok, kps, vps, lengths = carry
                caches = [(k, v, pt) for k, v in zip(kps, vps)]
                logits, new = run(params, buffers, tok[:, None], caches,
                                  lengths, None)
                kps = [c[0] for c in new]
                vps = [c[1] for c in new]
                nxt = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (nxt, kps, vps, lengths + 1), nxt

            (tok, kps, vps, lengths), toks = jax.lax.scan(
                body, (tok, k_pools, v_pools, lengths), None, length=n)
            return jnp.swapaxes(toks, 0, 1), kps, vps

        self._programs[key] = decode
        return decode

    # --- intake -------------------------------------------------------------
    def submit(self, input_ids, max_new_tokens=32, eos_token_id=None,
               request_id=None) -> RequestHandle:
        """Enqueue one sequence; returns its `RequestHandle`.  Raises
        ValueError when the request can never fit (prompt+max_new over
        the engine's per-sequence or pool capacity) — feasibility is
        checked at the door so the scheduler never deadlocks on an
        unservable request."""
        seq = Sequence(input_ids, max_new_tokens,
                       eos_token_id=eos_token_id, request_id=request_id)
        need = -(-(seq.prompt.size + seq.max_new_tokens)
                 // self.config.page_size)
        if need > self.pool.capacity:
            raise ValueError(
                f"request needs {need} pages, pool holds "
                f"{self.pool.capacity}")
        handle = RequestHandle(seq)
        seq.handle = handle
        # register BEFORE the scheduler can see the sequence: with the
        # loop thread running, a short request can be admitted,
        # finished, and its handle popped before submit() returns — a
        # post-hoc insert would leave a stale entry in _handles forever
        with self._lock:
            self._handles[seq.request_id] = handle
        try:
            self.scheduler.submit(seq)  # validates vs max_pages_per_seq
        except Exception:
            with self._lock:
                self._handles.pop(seq.request_id, None)
            raise
        _metrics.inc("engine.sequences", event="submitted")
        with self._work:
            self._work.notify_all()
        return handle

    def cancel(self, request_id) -> bool:
        """Abandon a sequence (client gone / explicit cancel): its
        handle completes as cancelled now; slot and pages return to the
        pool at the next schedule()."""
        ok = self.scheduler.cancel(request_id)
        if ok:
            _metrics.inc("engine.sequences", event="cancelled")
            with self._lock:
                handle = self._handles.pop(request_id, None)
            if handle is not None:
                handle._finish("cancelled")
            with self._work:
                self._work.notify_all()
        return ok

    # --- the engine step ----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: schedule -> prefill admissions ->
        ragged decode chunk -> detokenize/deliver.  Returns True when
        any work happened."""
        with self._lock:
            with _trace.span("engine.schedule", cat="engine"):
                out = self.scheduler.schedule(self.config.decode_chunk)
            for seq in out.evicted:
                _metrics.inc("engine.sequences", event="evicted")
            for seq in out.finished:
                # released this schedule (completed earlier, or
                # cancelled while waiting/running): close the handle
                # and drop the engine's reference — a long-running
                # server must not accumulate one handle per cancelled
                # request
                self._handles.pop(seq.request_id, None)
                if seq.handle is not None:
                    seq.handle._finish(seq.finish_reason or "finished")
            did = bool(out.finished or out.evicted)
            for seq in out.prefills:
                self._prefill(seq)
                did = True
            running = [s for s in out.running
                       if not s.done and s.slot is not None]
            if running:
                self._decode(running)
                did = True
            # free completed sequences' slots/pages NOW, not at the
            # next schedule — a drained engine must hold zero pages
            self.scheduler.release_finished()
            if did:
                self.steps += 1
            self._publish_gauges()
        return did

    def _bucket(self, s0: int) -> int:
        b = self.config.prefill_bucket
        return -(-s0 // b) * b

    def _prefill(self, seq: Sequence) -> None:  # pt-lint: ok[PT101,PT102] (step holds _lock)
        prompt = seq.resume_prompt()
        s0 = prompt.size
        sb = self._bucket(s0)
        start = sb - s0
        with _trace.span("engine.prefill", cat="engine",
                         request=seq.request_id, tokens=s0, bucket=sb,
                         pages=len(seq.pages)):
            ids = np.zeros((1, sb), np.int32)
            ids[0, start:] = prompt
            prefill = self._prefill_program(sb)
            tok, kbufs, vbufs = prefill(
                self._params, self._buffers, jnp.asarray(ids),
                jnp.asarray([start], jnp.int32))
            ps = self.config.page_size
            npb = -(-sb // ps)
            pages = np.zeros((npb,), np.int32)
            n_real = min(len(seq.pages), npb)
            pages[:n_real] = seq.pages[:n_real]
            pack = self._pack_program(sb)
            self._k_pools, self._v_pools = pack(
                self._k_pools, self._v_pools, kbufs, vbufs,
                jnp.asarray(pages), jnp.asarray(start, jnp.int32))
            seq.length = s0
            t0 = int(np.asarray(jax.device_get(tok))[0])
            seq.last_token = t0
        _metrics.inc("engine.sequences", event="admitted")
        self._accept(seq, t0)

    def _decode(self, running) -> None:  # pt-lint: ok[PT101,PT102] (step holds _lock)
        cfg = self.config
        s_, p_ = cfg.max_slots, self.max_pages_per_seq
        tok = np.zeros((s_,), np.int32)
        pt = np.zeros((s_, p_), np.int32)
        lengths = np.zeros((s_,), np.int32)
        for seq in running:
            tok[seq.slot] = seq.last_token
            pt[seq.slot, :len(seq.pages)] = seq.pages
            lengths[seq.slot] = seq.length
        # ALWAYS dispatch the configured chunk: shrinking the scan to
        # the batch's max remaining would compile one program per
        # distinct tail length — a compile per shape costs far more
        # than the few discarded tail tokens, and a single decode
        # program is the fixed-compiled-shape contract
        n = cfg.decode_chunk
        decode = self._decode_program(n)
        with _trace.span("engine.decode", cat="engine", batch=len(running),
                         chunk=n, occupancy=len(running) / cfg.max_slots):
            toks, self._k_pools, self._v_pools = decode(
                self._params, self._buffers, self._k_pools,
                self._v_pools, jnp.asarray(tok), jnp.asarray(pt),
                jnp.asarray(lengths))
        with _trace.span("engine.detokenize", cat="engine",
                         batch=len(running), chunk=n):
            toks = np.asarray(jax.device_get(toks))
            for seq in running:
                row = toks[seq.slot]
                for j in range(n):
                    if seq.done:
                        break  # mid-chunk finish: later tokens are the
                        # frozen-slot continuation, not output
                    self._accept(seq, int(row[j]))
                seq.length += n
                seq.last_token = int(row[n - 1])

    def _accept(self, seq: Sequence, tok: int) -> None:
        """One generated token passes the host: record, deliver,
        finish on eos / length (mirrors generate()'s freezing: the eos
        itself is emitted, nothing after it)."""
        seq.tokens.append(int(tok))
        _metrics.inc("engine.tokens")
        if seq.handle is not None:
            seq.handle._push(tok)
        if seq.eos_token_id is not None and int(tok) == seq.eos_token_id:
            self._finish(seq, "eos")
        elif len(seq.tokens) >= seq.max_new_tokens:
            self._finish(seq, "length")

    def _finish(self, seq: Sequence, reason: str) -> None:
        self.scheduler.finish(seq, reason)
        _metrics.inc("engine.sequences", event="completed")
        if seq.handle is not None:
            seq.handle._finish(reason)
        with self._lock:
            self._handles.pop(seq.request_id, None)

    def _publish_gauges(self) -> None:
        st = self.scheduler.stats()
        _metrics.set_gauge("engine.active_sequences", st["running"])
        _metrics.set_gauge("engine.waiting_sequences", st["waiting"])
        _metrics.set_gauge("engine.batch_occupancy", st["occupancy"])
        _metrics.set_gauge("engine.page_utilization",
                           self.pool.utilization())

    # --- maintenance --------------------------------------------------------
    def defrag(self) -> int:
        """Compact live pages to the densest pool prefix: apply the
        allocator's moves to the device pools and every live page
        table.  Returns the number of pages moved."""
        with self._lock:
            moves = self.pool.defrag()
            if not moves:
                return 0
            # ascending-dst order is overwrite-safe: src > dst always,
            # and every src exceeds all earlier dsts
            for src, dst in sorted(moves.items(), key=lambda kv: kv[1]):
                self._k_pools = [p.at[dst].set(p[src])
                                 for p in self._k_pools]
                self._v_pools = [p.at[dst].set(p[src])
                                 for p in self._v_pools]
            for seq in self.scheduler.running_seqs():
                seq.pages = [moves.get(p, p) for p in seq.pages]
        return len(moves)

    # --- loop / lifecycle ---------------------------------------------------
    def start(self):
        """Run the engine loop on a daemon thread (the serving mode);
        `step()` remains callable inline for tests."""
        with self._lock:
            if self._thread is not None:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="paddle-tpu-engine")
            self._thread.start()
        return self

    def _loop(self):
        # _running is a stop flag: a stale read costs one extra step;
        # taking the lock here would serialize the loop against submit()
        while self._running:  # pt-lint: ok[PT102]
            if not self.step():
                with self._work:
                    if self._running and not self.scheduler.has_work():
                        self._work.wait(timeout=0.05)

    def stop(self, timeout: float = 10.0):
        with self._lock:
            self._running = False
            thread = self._thread
            self._thread = None
        with self._work:
            self._work.notify_all()
        if thread is not None:
            thread.join(timeout=timeout)

    # --- convenience (tests / bench / equivalence) --------------------------
    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 timeout: float = 300.0):
        """Submit every prompt and run the engine to completion
        (inline when the loop thread is not running).  Returns a list
        of int32 [s0_i + n_generated_i] arrays — `generate()`-shaped
        output for direct equivalence checks."""
        handles = [self.submit(p, max_new_tokens,
                               eos_token_id=eos_token_id)
                   for p in prompts]
        # _thread is set-once before any submit in the loop-thread
        # mode; inline callers never race it
        if self._thread is None:  # pt-lint: ok[PT102]
            idle = 0
            while any(not h.done.is_set() for h in handles):
                if self.step():
                    idle = 0
                else:
                    idle += 1
                    if idle > 1000:
                        raise RuntimeError(
                            "engine made no progress (scheduler stuck)")
        return [h.result(timeout=timeout) for h in handles]

    def stats(self) -> dict:
        st = self.scheduler.stats()
        st["pages"] = self.pool.stats()
        # monotonic int snapshot for telemetry; a stale read is a fine
        # answer to "how many steps so far"
        st["steps"] = self.steps  # pt-lint: ok[PT102]
        return st
