"""Continuous-batching inference engine over a paged KV cache.

The serving-side answer to ROADMAP item 1: instead of one predictor
lock serving whole `generate()` calls back-to-back, the engine keeps a
FIXED compiled batch of sequence slots and advances every in-flight
sequence one (or `decode_chunk`) token(s) per step, admitting new
sequences into freed slots between steps — throughput scales with
batch occupancy, latency with queue position, and no request waits for
the longest one to finish.

Three compiled programs (per shape signature, cached):

  * **prefill** (one sequence, prompt left-padded to a bucket): the
    dense static-cache path the model families already compile —
    returns the first generated token and the dense K/V it produced.
  * **pack**: scatters the fresh dense K/V into the sequence's
    allocated pages (pools donated — in-place on TPU).
  * **decode** (the hot step): `decode_chunk` scanned steps at the
    fixed `[max_slots]` batch — each step writes every slot's current
    token into its page at `page_table[slot, len//ps], len%ps` and
    attends through `ops/pallas/paged_attention` with per-slot ragged
    lengths.  Pools donated; tokens stay on device across the scan.

Free slots ride along pointing at the reserved scratch page with
length 0: their output is discarded on the host, and the compiled
shape never changes as sequences come and go.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_ENGINE_PAGE_SIZE       tokens per KV page        (16)
  PADDLE_TPU_ENGINE_MAX_PAGES      pool size incl. scratch    (derived)
  PADDLE_TPU_ENGINE_MAX_SLOTS      compiled batch slots       (4)
  PADDLE_TPU_ENGINE_DECODE_CHUNK   decode steps per dispatch  (1)
  PADDLE_TPU_ENGINE_PREFILL_BUCKET prompt padding granule     (16)
  PADDLE_TPU_ENGINE_MAX_SEQ_LEN    per-sequence token cap     (model's)
  PADDLE_TPU_ENGINE_PREFIX_CACHE   prefix caching on/off      (1)
  PADDLE_TPU_ENGINE_PREFIX_CACHE_MAX_TOKENS  cache bound      (0=pool)

Observability: `engine.schedule/prefill/decode/detokenize` spans on
the request-trace timeline, `engine.*` gauges (active/waiting
sequences, page utilization, batch occupancy) and counters
(`engine.sequences{event}`, `engine.tokens`) in the attach() schema.
"""
from __future__ import annotations

import functools
import os
import queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ...observability import metrics as _metrics
from ...observability import tenant_ledger as _tledger
from ...observability import trace as _trace
from ...observability import xla_cost as _xla_cost
from ...observability.timeseries import DecisionRing, RequestTimeline
from ...resilience.overload import _env_num
from .paging import PagePool
from .prefix import PrefixIndex
from .scheduler import Scheduler, Sequence

__all__ = ["EngineConfig", "InferenceEngine", "RequestHandle"]

# completed-request timelines retained for GET /debug/requests/<id>
_TIMELINE_LRU = 128


def _precision_knob(explicit, env, valid):
    """Resolve a precision-tier knob (explicit arg wins, else env).
    Invalid values fail LOUDLY at engine build, not mid-decode —
    the same discipline as `distributed.quantized.collective_precision`."""
    raw = explicit if explicit is not None else os.environ.get(env, "")
    key = str(raw).strip().lower()
    if key not in valid:
        raise ValueError(
            f"{env}={raw!r}: expected one of "
            f"{sorted(k for k in valid if k)} (or unset for the exact "
            f"tier)")
    return valid[key]


class EngineConfig:
    """Engine sizing knobs; every ctor arg falls back to its
    PADDLE_TPU_ENGINE_* env, then the default."""

    def __init__(self, page_size=None, num_pages=None, max_slots=None,
                 decode_chunk=None, prefill_bucket=None,
                 max_seq_len=None, weight_precision=None,
                 kv_precision=None, spec_tokens=None, pool_hbm_mb=None,
                 prefix_cache=None, prefix_cache_max_tokens=None):
        self.page_size = int(page_size if page_size is not None else
                             _env_num("PADDLE_TPU_ENGINE_PAGE_SIZE", 16,
                                      int))
        self.max_slots = int(max_slots if max_slots is not None else
                             _env_num("PADDLE_TPU_ENGINE_MAX_SLOTS", 4,
                                      int))
        self.decode_chunk = int(
            decode_chunk if decode_chunk is not None else
            _env_num("PADDLE_TPU_ENGINE_DECODE_CHUNK", 1, int))
        self.prefill_bucket = int(
            prefill_bucket if prefill_bucket is not None else
            _env_num("PADDLE_TPU_ENGINE_PREFILL_BUCKET", 16, int))
        # 0 = resolve from the model's max_seq_len at engine build
        self.max_seq_len = int(
            max_seq_len if max_seq_len is not None else
            _env_num("PADDLE_TPU_ENGINE_MAX_SEQ_LEN", 0, int))
        # 0 = derived: every slot can hold a max-length sequence
        self.num_pages = int(num_pages if num_pages is not None else
                             _env_num("PADDLE_TPU_ENGINE_MAX_PAGES", 0,
                                      int))
        # quantized decode tiers (ISSUE 12, docs/INFERENCE.md):
        #   weight_precision: int8 = per-output-channel weight-only
        #     quantization of every matmul weight at engine build,
        #     dequant fused inside the decode GEMVs; bf16 = plain cast.
        #   kv_precision: int8 = the page pools store int8 with
        #     per-token-per-head scales next to the page table.
        self.weight_precision = _precision_knob(
            weight_precision, "PADDLE_TPU_ENGINE_WEIGHT_PRECISION",
            {"": None, "f32": None, "full": None, "fp32": None,
             "bf16": "bf16", "int8": "int8"})
        self.kv_precision = _precision_knob(
            kv_precision, "PADDLE_TPU_ENGINE_KV_PRECISION",
            {"": None, "f32": None, "full": None, "fp32": None,
             "int8": "int8"})
        # draft-model speculative decoding: tokens proposed per pass
        # (0 = off; needs a draft_model at engine construction)
        self.spec_tokens = int(
            spec_tokens if spec_tokens is not None else
            _env_num("PADDLE_TPU_ENGINE_SPEC_TOKENS", 0, int))
        # fixed page-pool HBM budget in MiB (0 = unset): when num_pages
        # is not given explicitly, the pool is sized to FIT this budget
        # under the active kv tier — so int8 pages buy ~2x the pages
        # (and in-flight sequences) of bf16 for the same bytes, which
        # is the capacity claim the scheduler test asserts
        self.pool_hbm_mb = float(
            pool_hbm_mb if pool_hbm_mb is not None else
            _env_num("PADDLE_TPU_ENGINE_POOL_HBM_MB", 0.0, float))
        # prefix caching (ISSUE 13, docs/INFERENCE.md "Prefix caching"):
        # committed page-aligned prompt prefixes are indexed and shared
        # into later sequences' page tables (refcounted), so prefill
        # compute and page capacity scale with UNIQUE prompt tokens.
        # ON by default — streams are proven bit-identical warm vs
        # cold; 0 disables.  The token bound caps what the radix index
        # may pin (0 = bounded only by pool pressure's LRU reclaim).
        self.prefix_cache = bool(int(
            prefix_cache if prefix_cache is not None else
            _env_num("PADDLE_TPU_ENGINE_PREFIX_CACHE", 1, int)))
        self.prefix_cache_max_tokens = int(
            prefix_cache_max_tokens
            if prefix_cache_max_tokens is not None else
            _env_num("PADDLE_TPU_ENGINE_PREFIX_CACHE_MAX_TOKENS", 0,
                     int))
        for name in ("page_size", "max_slots", "decode_chunk",
                     "prefill_bucket"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got "
                                 f"{getattr(self, name)}")
        if self.spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {self.spec_tokens}")
        if self.prefix_cache_max_tokens < 0:
            raise ValueError(
                f"prefix_cache_max_tokens must be >= 0, got "
                f"{self.prefix_cache_max_tokens}")


class RequestHandle:
    """One submitted request's delivery side: a token stream plus a
    completion event.  Tokens arrive as the engine accepts them;
    `result()` blocks for the full prompt+generated ids."""

    def __init__(self, seq: Sequence):
        self._seq = seq
        self.request_id = seq.request_id
        self.tenant_id = getattr(seq, "tenant_id", None)
        self._q = queue.Queue()
        self.done = threading.Event()
        self.finish_reason = None

    def _push(self, tok: int) -> None:
        self._q.put(int(tok))

    def _finish(self, reason: str) -> None:
        if self.done.is_set():
            return
        self.finish_reason = reason
        self.done.set()
        self._q.put(None)          # stream sentinel

    # --- consumer side ------------------------------------------------------
    def stream(self, timeout: float = 120.0):
        """Yield generated tokens as they land; returns at completion."""
        while True:
            tok = self._q.get(timeout=timeout)
            if tok is None:
                return
            yield tok

    def result(self, timeout: float = 120.0) -> np.ndarray:
        """Blocking: full int32 [s0 + n_generated] ids (prompt
        included, like `GenerationMixin.generate`)."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        return self._seq.output_ids()

    @property
    def tokens(self) -> list:
        return list(self._seq.tokens)

    @property
    def cancelled(self) -> bool:
        return self.finish_reason == "cancelled"

    @property
    def cache_state(self) -> str:
        """Prefix-cache outcome at admission: ``hit`` (longest sharable
        prefix fully cached), ``partial``, or ``miss`` (also the answer
        while still waiting / when caching is off) — the TTFT
        histogram's `cache` label (serving.py)."""
        return self._seq.cache_state or "miss"


def _matmul_weight_names(model):
    """Param names of the model's matmul weights — the HBM stream the
    weight-only tier halves: every Linear-family 2-D weight, plus the
    tied-embedding LM head (contracted on its hidden axis).  Returns
    ``{name: contraction_axis}``."""
    from ...distributed import mpu
    from ...nn.layers_common import Embedding, Linear

    linear_types = (Linear, mpu.ColumnParallelLinear,
                    mpu.RowParallelLinear)
    emb_types = (Embedding, mpu.VocabParallelEmbedding)
    names = {}
    vocab = int(getattr(getattr(model, "cfg", None), "vocab_size", 0))
    tied = bool(getattr(getattr(model, "cfg", None), "tie_embeddings",
                        False))
    for prefix, layer in model.named_sublayers(include_self=True):
        w = getattr(layer, "weight", None)
        if w is None or w._value.ndim != 2:
            continue
        if not jnp.issubdtype(w._value.dtype, jnp.floating):
            continue
        name = f"{prefix}.weight" if prefix else "weight"
        if isinstance(layer, linear_types):
            names[name] = 0          # [in, out]: contract over in
        elif tied and isinstance(layer, emb_types) \
                and w._value.shape[0] == vocab:
            # the tied embedding doubles as the LM head
            # (`x.matmul(w, transpose_y=True)`): output channels are
            # vocab ROWS, so the scale is per row (absmax over hidden)
            # — and the embedding lookup dequantizes the same rows with
            # the same scales, so both uses stay consistent
            names[name] = 1
    return names


class InferenceEngine:
    """Continuous-batching engine over one `GenerationMixin` model
    (greedy decoding — the deterministic serving mode; sampling rides
    ROADMAP item 4).

    Quantized decode tiers (ISSUE 12):
      * ``config.weight_precision='int8'`` quantizes every matmul
        weight ONCE at construction (per-output-channel absmax scales,
        `ops/quant.py` codec); the dequant runs inside the compiled
        decode scan body so the weights stream from HBM as int8.
      * ``config.kv_precision='int8'`` stores the KV page pools as int8
        with per-token-per-head scale tables riding next to the page
        table — half the page HBM, ~2x the in-flight sequences per
        fixed ``pool_hbm_mb`` budget.
      * ``draft_model=`` + ``config.spec_tokens=k`` turns on greedy
        speculative decoding: the draft proposes k tokens per slot per
        pass, the target scores all k+1 positions in ONE batched ragged
        paged-attention pass (positions spread over the batch axis so
        each row computes exactly what a sequential step would), and
        the accepted prefix commits on device — the committed stream is
        bit-identical to sequential greedy by construction.
    """

    def __init__(self, model, config: EngineConfig = None,
                 clock=time.monotonic, draft_model=None):
        import copy

        # own copy: max_seq_len/num_pages resolve against THIS model
        # below, and mutating the caller's object would poison a config
        # reused for a second engine over a different model
        self.config = copy.copy(config) if config is not None \
            else EngineConfig()
        self._model = model
        model.eval()
        self._params, self._buffers = model.functional_state()
        cfg = self.config
        # shape probe: one layer's dense cache tells us layers/heads/dim
        probe = model.init_kv_caches(1, 1)
        self._layers = len(probe)
        _, self._hkv, _, self._hd = probe[0][0].shape
        self._dtype = probe[0][0].dtype
        del probe
        if cfg.max_seq_len <= 0:
            cfg.max_seq_len = int(getattr(model.cfg, "max_seq_len", 0)) \
                or 2048
        # --- weight-only quantization (once, at build) -------------------
        self._wq_meta = {}
        if cfg.weight_precision is not None:
            self._quantize_weights()
        # --- draft model (speculative decoding) --------------------------
        self._draft = None
        if cfg.spec_tokens > 0:
            if draft_model is None:
                raise ValueError(
                    "spec_tokens > 0 needs a draft_model at engine "
                    "construction")
            self._init_draft(draft_model)
        elif draft_model is not None:
            raise ValueError(
                "draft_model given but config.spec_tokens == 0 — set "
                "spec_tokens (or PADDLE_TPU_ENGINE_SPEC_TOKENS) to the "
                "draft proposal length")
        self.max_pages_per_seq = -(-cfg.max_seq_len // cfg.page_size)
        if cfg.num_pages <= 0:
            if cfg.pool_hbm_mb > 0:
                # size the pool to FIT the byte budget under the active
                # kv tier: int8 pages cost ~half of bf16 (+ the f32
                # scale sidecar), so the same budget admits ~2x pages
                per_page = self._page_bytes()
                cfg.num_pages = max(
                    2, int(cfg.pool_hbm_mb * 2**20) // per_page)
            else:
                cfg.num_pages = cfg.max_slots * self.max_pages_per_seq + 1
        self.pool = PagePool(cfg.num_pages, cfg.page_size)
        self._prefix = None
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_tokens_saved = 0
        self._prefix_tokens_total = 0
        if cfg.prefix_cache:
            self._prefix = PrefixIndex(
                self.pool, max_tokens=cfg.prefix_cache_max_tokens,
                clock=clock,
                on_evict=lambda n: _metrics.inc(
                    "engine.prefix_cache", n, event="evict"))
        self._clock = clock
        # per-token latency attribution (ISSUE 15): the scheduler's
        # bounded decision ring + a bounded LRU of per-request
        # timelines — what GET /debug/requests/<id> correlates.
        # PADDLE_TPU_ITL_TIMELINE_CAP=0 disables timeline stamping.
        self.decisions = DecisionRing(capacity=512, clock=clock)
        self._timeline_cap = int(_env_num(
            "PADDLE_TPU_ITL_TIMELINE_CAP", 256, int))
        self._timelines = {}       # request_id -> RequestTimeline (LRU)
        # per-tenant metering (ISSUE 16): the engine owns the process's
        # book — decode tokens bill here (`record_decode` also owns the
        # engine.tokens increment, see tenant_ledger docstring), the
        # scheduler integrates KV page-seconds against it, and serving
        # ADOPTS it so edge request billing shares the same book (the
        # conservation invariant is per-book).  None when the plane is
        # off: a detached process pays nothing, not even O(K).
        self.tenant_ledger = None
        if _tledger.enabled() and _metrics.enabled():
            self.tenant_ledger = _tledger.TenantLedger()
        self.scheduler = Scheduler(cfg.max_slots, self.pool,
                                   self.max_pages_per_seq, clock=clock,
                                   prefix_index=self._prefix,
                                   decision_ring=self.decisions,
                                   tenant_ledger=self.tenant_ledger)
        shape = (cfg.num_pages, self._hkv, cfg.page_size, self._hd)
        pool_dtype = jnp.int8 if cfg.kv_precision == "int8" \
            else self._dtype
        self._k_pools = [jnp.zeros(shape, pool_dtype)
                         for _ in range(self._layers)]
        self._v_pools = [jnp.zeros(shape, pool_dtype)
                         for _ in range(self._layers)]
        self._k_scales = self._v_scales = None
        if cfg.kv_precision == "int8":
            # scale 1 everywhere: a never-written (scratch) slot
            # decodes to exact zeros, like the bf16 pool's zeros
            sshape = shape[:3]
            self._k_scales = [jnp.ones(sshape, jnp.float32)
                              for _ in range(self._layers)]
            self._v_scales = [jnp.ones(sshape, jnp.float32)
                              for _ in range(self._layers)]
        if self._draft is not None:
            self._init_draft_pools()
        self._programs = {}
        self._handles = {}         # request_id -> RequestHandle
        self._lock = threading.RLock()
        self._work = threading.Condition()
        self._thread = None
        self._running = False
        self.steps = 0
        self._publish_tier_gauges()

    # --- quantized-tier construction ----------------------------------------
    def _page_bytes(self) -> int:  # pt-lint: ok[PT102] (_draft binding is set once at construction; only its set-once geometry keys are read here — the mutable k/v pools stay under _lock)
        """HBM bytes ONE page costs across all layers (K+V pools plus
        the scale sidecar under the int8 kv tier, plus the draft
        model's pools when speculative decoding shares the page table)
        — the unit the ``pool_hbm_mb`` budget divides."""
        cfg = self.config
        if cfg.kv_precision == "int8":
            item, scale_item = 1, 4
        else:
            item = jnp.dtype(self._dtype).itemsize
            scale_item = 0
        per_pool = self._hkv * cfg.page_size * self._hd * item \
            + self._hkv * cfg.page_size * scale_item
        total = self._layers * 2 * per_pool
        if self._draft is not None:
            d = self._draft
            total += d["layers"] * 2 * (
                d["hkv"] * cfg.page_size * d["hd"]
                * jnp.dtype(d["dtype"]).itemsize)
        return max(1, total)

    def _quantize_weights(self) -> None:
        """Swap every matmul weight in the params pytree for its
        quantized form ({"q": int8, "s": f32 broadcastable} leaves for
        int8; a plain bf16 cast for bf16).  `_dequant_params` is the
        traced inverse — running INSIDE the compiled programs, so the
        stored (and HBM-streamed) representation stays narrow."""
        from ...ops import quant as QT

        prec = self.config.weight_precision
        names = _matmul_weight_names(self._model)
        for name, axis in names.items():
            w = self._params.get(name)
            if w is None:
                continue
            if prec == "int8":
                q, s = QT.quantize_channels(w, axis=axis)
                self._params[name] = {"q": q, "s": s}
            else:
                self._params[name] = {"q": w.astype(jnp.bfloat16)}
            self._wq_meta[name] = str(w.dtype)

    def _dequant_params(self, params):
        """Traced: rebuild full-precision weights from the quantized
        leaves.  Called INSIDE every compiled program (for the decode
        scan: inside the scan body), so XLA keeps the int8->float
        convert next to the GEMV instead of materializing a
        full-precision weight copy in HBM — `perf_audit`'s
        ``gpt_quantized_decode_step`` program pins this placement."""
        if not self._wq_meta:
            return params
        from ...ops import quant as QT

        out = dict(params)
        for name, dt in self._wq_meta.items():
            leaf = params[name]
            if "s" in leaf:
                out[name] = QT.dequantize_channels(leaf["q"], leaf["s"],
                                                   dtype=dt)
            else:
                out[name] = leaf["q"].astype(dt)
        return out

    def effective_params(self):
        """The de-quantized params the engine's programs actually
        compute with (identity when no weight tier is active) — bind
        these into the model to reproduce engine streams with plain
        `generate()` (the per-tier equivalence tests do exactly that)."""
        return self._dequant_params(self._params)

    def _init_draft(self, draft_model) -> None:
        draft_model.eval()
        dparams, dbuffers = draft_model.functional_state()
        probe = draft_model.init_kv_caches(1, 1)
        tv = int(getattr(getattr(self._model, "cfg", None),
                         "vocab_size", 0))
        dv = int(getattr(getattr(draft_model, "cfg", None),
                         "vocab_size", 0))
        if tv and dv and tv != dv:
            raise ValueError(
                f"draft vocab_size {dv} != target vocab_size {tv} — "
                f"proposals would index a different token space")
        self._draft = {
            "model": draft_model,
            "params": dparams,
            "buffers": dbuffers,
            "layers": len(probe),
            "hkv": probe[0][0].shape[1],
            "hd": probe[0][0].shape[3],
            "dtype": probe[0][0].dtype,
        }
        del probe

    def _init_draft_pools(self) -> None:
        """Draft KV pools share the page table/allocator with the
        target's (same page ids, own geometry) — allocation bookkeeping
        stays single.  The draft is small, so its pools stay full
        precision."""
        d = self._draft
        cfg = self.config
        shape = (cfg.num_pages, d["hkv"], cfg.page_size, d["hd"])
        d["k_pools"] = [jnp.zeros(shape, d["dtype"])
                        for _ in range(d["layers"])]
        d["v_pools"] = [jnp.zeros(shape, d["dtype"])
                        for _ in range(d["layers"])]

    def _publish_tier_gauges(self) -> None:
        cfg = self.config
        _metrics.set_gauge("engine.weight_precision", 1,
                           precision=cfg.weight_precision or "full")
        _metrics.set_gauge("paged.pool_precision", 1,
                           precision=cfg.kv_precision or "full")
        _metrics.set_gauge("engine.spec_tokens", cfg.spec_tokens)

    # --- model invocation (raw jax values; paged or dense caches) -----------
    def _run_model(self, params, buffers, ids, caches, pos, start):
        from ...core import flags
        from ...core.tensor import Tensor

        params = self._dequant_params(params)
        with flags.no_grad_guard(), flags.trace_guard():
            with self._model.bind_state(params, buffers):
                logits, new = self._model(
                    Tensor(ids),
                    kv_caches=[tuple(Tensor(x) for x in c)
                               for c in caches],
                    cache_pos=Tensor(pos),
                    attn_start=None if start is None else Tensor(start))
        return logits._value, [tuple(x._value for x in c) for c in new]

    def _run_draft(self, params, buffers, ids, caches, pos, start):  # pt-lint: ok[PT102] (_draft binding and its "model" key are set once at construction and never rebound)
        from ...core import flags
        from ...core.tensor import Tensor

        model = self._draft["model"]
        with flags.no_grad_guard(), flags.trace_guard():
            with model.bind_state(params, buffers):
                logits, new = model(
                    Tensor(ids),
                    kv_caches=[tuple(Tensor(x) for x in c)
                               for c in caches],
                    cache_pos=Tensor(pos),
                    attn_start=None if start is None else Tensor(start))
        return logits._value, [tuple(x._value for x in c) for c in new]

    # --- compiled programs --------------------------------------------------
    def _which(self, which):  # pt-lint: ok[PT102] (_draft binding and its geometry keys are set once at construction)
        """(run_fn, layers, hkv, hd, dtype) for "target"/"draft"."""
        if which == "draft":
            d = self._draft
            return (self._run_draft, d["layers"], d["hkv"], d["hd"],
                    d["dtype"])
        return (self._run_model, self._layers, self._hkv, self._hd,
                self._dtype)

    def _caches_of(self, kps, vps, pt, kss=None, vss=None):
        """Per-layer cache tuples for the paged model path: 5-tuples
        (with scale tables) under the int8 kv tier, 3-tuples otherwise."""
        if kss:
            return [(k, v, pt, ks, vs) for k, v, ks, vs
                    in zip(kps, vps, kss, vss)]
        return [(k, v, pt) for k, v in zip(kps, vps)]

    def _prefill_program(self, sb: int, which="target"):
        """One left-padded sequence at bucket length sb: greedy first
        token + the dense K/V (capacity sb+page_size so the pack
        program's last page slice never clamps)."""
        key = ("prefill", sb, which)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        run, layers, hkv, d, dtype = self._which(which)
        cap = sb + self.config.page_size

        @jax.jit
        def prefill(params, buffers, ids, start):
            caches = [(jnp.zeros((1, hkv, cap, d), dtype),
                       jnp.zeros((1, hkv, cap, d), dtype))
                      for _ in range(layers)]
            logits, new = run(params, buffers, ids, caches,
                              jnp.zeros((), jnp.int32), start)
            tok = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return tok, [c[0] for c in new], [c[1] for c in new]

        label = f"prefill_s{sb}" + ("" if which == "target" else f"_{which}")
        prefill = _xla_cost.instrument(prefill, label)
        # pt-lint: ok[PT503] (benign memo race: dict set is atomic in CPython; worst case two threads jit the same program once each)
        self._programs[key] = prefill
        return prefill

    def _pack_program(self, sb: int, which="target"):
        """Scatter a prefill's dense K/V (real tokens at
        [start, start+s0)) into the sequence's pages.  Pages beyond the
        prompt's span point at the scratch page — their writes are
        discarded by construction.  Under the int8 kv tier each token's
        head-vector quantizes independently (`quantize_vectors` — the
        SAME per-vector codec the decode write applies), so the packed
        page content is bit-identical to what token-by-token writes
        would have produced."""
        quant = which == "target" and self.config.kv_precision == "int8"
        key = ("pack", sb, which, quant)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        ps = self.config.page_size
        _, _, hkv, d, _ = self._which(which)
        npb = -(-sb // ps)

        if not quant:
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def pack(k_pools, v_pools, kbufs, vbufs, pages, start):
                def put(pool, buf):
                    def body(i, pool):
                        chunk = jax.lax.dynamic_slice(
                            buf, (0, 0, start + i * ps, 0),
                            (1, hkv, ps, d))
                        return jax.lax.dynamic_update_slice(
                            pool, chunk.astype(pool.dtype),
                            (pages[i], 0, 0, 0))
                    return jax.lax.fori_loop(0, npb, body, pool)

                k_pools = [put(p, b) for p, b in zip(k_pools, kbufs)]
                v_pools = [put(p, b) for p, b in zip(v_pools, vbufs)]
                return k_pools, v_pools

            label = f"pack_s{sb}" + ("" if which == "target" else f"_{which}")
            pack = _xla_cost.instrument(pack, label)
            self._programs[key] = pack
            return pack

        from ...ops.quant import quantize_vectors

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def pack_q(k_pools, v_pools, k_scales, v_scales, kbufs, vbufs,
                   pages, start):
            def put(pool, scales, buf):
                def body(i, carry):
                    pool, scales = carry
                    chunk = jax.lax.dynamic_slice(
                        buf, (0, 0, start + i * ps, 0),
                        (1, hkv, ps, d))[0]          # [hkv, ps, d]
                    # per-(head, token) vector scales — one absmax per
                    # d-vector, independent of neighbours
                    qv, sv = quantize_vectors(chunk)
                    pool = jax.lax.dynamic_update_slice(
                        pool, qv[None], (pages[i], 0, 0, 0))
                    scales = jax.lax.dynamic_update_slice(
                        scales, sv[None], (pages[i], 0, 0))
                    return pool, scales
                return jax.lax.fori_loop(0, npb, body, (pool, scales))

            ks, vs = list(k_scales), list(v_scales)
            kp = list(k_pools)
            vp = list(v_pools)
            for li in range(len(kp)):
                kp[li], ks[li] = put(kp[li], ks[li], kbufs[li])
                vp[li], vs[li] = put(vp[li], vs[li], vbufs[li])
            return kp, vp, ks, vs

        pack_q = _xla_cost.instrument(pack_q, f"pack_s{sb}_q")
        self._programs[key] = pack_q
        return pack_q

    def _cached_prefill_program(self, sb: int, npp: int,
                                which="target"):
        """WARM tail prefill (prefix caching, ISSUE 13): one sequence
        whose first `plen` tokens (page-aligned, `<= npp` pages) are
        already committed in the pools — only the tail (left-padded to
        bucket `sb`) runs through the model.  The cached prefix is
        gathered into a dense buffer at [0, plen) and the forward runs
        under `generation.warm_prefill_guard`, so every tail query
        attends prefix + causal tail; `cache_pos` starts at the shared
        length and the compiled shape depends only on (sb, npp) — npp
        is bucketed to a power of two by the caller, which is what the
        committed PT402 budget on `gpt_cached_prefill_step` pins.

        Exact tier (and the draft model): the prefix is gathered from
        the pools in-program — pools store full precision, so the
        gather IS the exact prefix.  int8-KV tier: the program instead
        takes per-layer EXACT prefix buffers (the radix index's commit
        -time sidecar) — a warm first token must attend the prefix at
        the same precision a cold prefill would, or warm and cold
        streams diverge beyond reduction-order noise."""
        quant = which == "target" and self.config.kv_precision == "int8"
        key = ("cprefill", sb, npp, which, quant)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        from ...models import generation as GEN

        run, layers, hkv, d, dtype = self._which(which)
        ps = self.config.page_size
        pcap = npp * ps

        def finish(logits, new):
            tok = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return tok, [c[0] for c in new], [c[1] for c in new]

        if quant:
            @jax.jit
            def cprefill_q(params, buffers, ids, start, plen,
                           prefix_k, prefix_v):
                def dense(buf):        # [npp, hkv, ps, d] exact sidecar
                    g = jnp.swapaxes(buf, 0, 1).reshape(hkv, pcap,
                                                        d)[None]
                    return jnp.concatenate(
                        [g.astype(dtype),
                         jnp.zeros((1, hkv, sb + ps, d), dtype)],
                        axis=2)

                caches = [(dense(prefix_k[li]), dense(prefix_v[li]))
                          for li in range(layers)]
                with GEN.warm_prefill_guard(plen):
                    logits, new = run(params, buffers, ids, caches,
                                      plen, start)
                return finish(logits, new)

            cprefill_q = _xla_cost.instrument(
                cprefill_q, f"cprefill_s{sb}_p{npp}_q")
            self._programs[key] = cprefill_q
            return cprefill_q

        @jax.jit
        def cprefill(params, buffers, ids, start, pages, plen,
                     k_pools, v_pools):
            def dense(pool):
                g = pool[pages]                    # [npp, hkv, ps, d]
                g = jnp.swapaxes(g, 0, 1).reshape(hkv, pcap, d)[None]
                return jnp.concatenate(
                    [g.astype(dtype),
                     jnp.zeros((1, hkv, sb + ps, d), dtype)], axis=2)

            caches = [(dense(k_pools[li]), dense(v_pools[li]))
                      for li in range(layers)]
            with GEN.warm_prefill_guard(plen):
                logits, new = run(params, buffers, ids, caches, plen,
                                  start)
            return finish(logits, new)

        label = f"cprefill_s{sb}_p{npp}" + (
            "" if which == "target" else f"_{which}")
        cprefill = _xla_cost.instrument(cprefill, label)
        self._programs[key] = cprefill
        return cprefill

    def _decode_program(self, n: int):
        """`n` ragged decode steps at the fixed [max_slots] batch inside
        one compiled scan.  Pools donated: each step writes one page
        slot per sequence per layer, and donation lets XLA update in
        place instead of copying the whole pool per token.  The
        weight-dequant (int8 tier) runs INSIDE the scan body via
        `_run_model`, so the int8->float convert stays fused next to
        each GEMV instead of materializing full-precision weights."""
        quant = self.config.kv_precision == "int8"
        key = ("decode", n, quant)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        run = self._run_model
        caches_of = self._caches_of

        @functools.partial(jax.jit, donate_argnums=(2, 3, 4, 5))
        def decode(params, buffers, k_pools, v_pools, k_scales,
                   v_scales, tok, pt, lengths):
            def body(carry, _):
                tok, kps, vps, kss, vss, lengths = carry
                caches = caches_of(kps, vps, pt, kss, vss)
                logits, new = run(params, buffers, tok[:, None], caches,
                                  lengths, None)
                kps = [c[0] for c in new]
                vps = [c[1] for c in new]
                if quant:
                    kss = [c[3] for c in new]
                    vss = [c[4] for c in new]
                nxt = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (nxt, kps, vps, kss, vss, lengths + 1), nxt

            (tok, kps, vps, kss, vss, lengths), toks = jax.lax.scan(
                body, (tok, k_pools, v_pools, k_scales, v_scales,
                       lengths), None, length=n)
            return jnp.swapaxes(toks, 0, 1), kps, vps, kss, vss

        decode = _xla_cost.instrument(decode, f"decode_n{n}")
        self._programs[key] = decode
        return decode

    def _spec_program(self, k: int):
        """One speculative-decoding pass at the fixed [max_slots]
        batch: the draft proposes ``k`` tokens (k+1 scanned single-token
        steps — the extra feed writes the last proposal's K/V so a
        fully-accepted pass leaves the draft cache complete), then the
        TARGET scores all k+1 positions in ONE batched ragged
        paged-attention pass with the positions spread over the batch
        axis — row (s, i) carries its own cache position L_s+i and
        slot s's page table, so each row computes EXACTLY what the
        sequential decode step at that position computes (same shapes,
        same masks), which is what makes the accepted stream
        bit-identical to sequential greedy.  Accept/reject runs on
        device; the host reads (g, counts) and commits g[:, :counts].
        """
        quant = self.config.kv_precision == "int8"
        key = ("spec", k, quant)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        run = self._run_model
        run_d = self._run_draft
        caches_of = self._caches_of
        s_ = self.config.max_slots

        @functools.partial(jax.jit,
                           donate_argnums=(4, 5, 6, 7, 8, 9))
        def spec(params, buffers, dparams, dbuffers, k_pools, v_pools,
                 k_scales, v_scales, dk_pools, dv_pools, tok, pt,
                 lengths, limits):
            # rows past a sequence's LIFETIME end (pos >= limits[s] =
            # prompt+max_new) are masked onto the scratch page at pos 0:
            # an unmasked overflow row's page-table gather would CLAMP
            # onto the row's last real page and its scatter would
            # overwrite a live committed position — which the same
            # pass's valid rows then attend (the batched pass writes
            # ALL rows before any row attends), silently breaking the
            # bit-identical-to-greedy contract on the final pass of a
            # table-filling sequence.  Masked rows' outputs are never
            # committed (a committed row always has pos < limit), so
            # scratch garbage is fine — the same contract free slots
            # already ride on.
            def mask_row(pos, table):
                ok = pos < limits
                return (jnp.where(ok, pos, 0),
                        jnp.where(ok[:, None], table, 0))

            # --- draft proposes (sequential tiny steps, one scan) ----
            def dbody(carry, _):
                cur, dkp, dvp, pos = carry
                pos_eff, pt_eff = mask_row(pos, pt)
                caches = caches_of(dkp, dvp, pt_eff)
                logits, new = run_d(dparams, dbuffers, cur[:, None],
                                    caches, pos_eff, None)
                dkp = [c[0] for c in new]
                dvp = [c[1] for c in new]
                nxt = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (nxt, dkp, dvp, pos + 1), nxt

            (_, dkp, dvp, _), d_all = jax.lax.scan(
                dbody, (tok, dk_pools, dv_pools, lengths), None,
                length=k + 1)
            props = jnp.swapaxes(d_all[:k], 0, 1)        # [S, k]
            # --- target scores k+1 positions in one ragged pass ------
            ids = jnp.concatenate([tok[:, None], props], axis=1)
            posm = lengths[:, None] + \
                jnp.arange(k + 1, dtype=jnp.int32)[None, :]
            bp = s_ * (k + 1)
            lim_f = jnp.repeat(limits, k + 1)
            pos_f = posm.reshape(bp)
            ok_f = pos_f < lim_f
            pos_f = jnp.where(ok_f, pos_f, 0)
            pt_f = jnp.where(ok_f[:, None],
                             jnp.repeat(pt, k + 1, axis=0), 0)
            caches = caches_of(k_pools, v_pools, pt_f, k_scales,
                               v_scales)
            logits, new = run(params, buffers,
                              ids.reshape(bp)[:, None], caches,
                              pos_f, None)
            kps = [c[0] for c in new]
            vps = [c[1] for c in new]
            kss = [c[3] for c in new] if quant else k_scales
            vss = [c[4] for c in new] if quant else v_scales
            g = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                           axis=-1).astype(jnp.int32).reshape(s_, k + 1)
            # --- greedy accept: longest prefix with d_{i+1} == g_i ---
            match = (props == g[:, :k]).astype(jnp.int32)
            acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            counts = acc + 1       # committed tokens = g[:, :acc+1]
            return g, counts, kps, vps, kss, vss, dkp, dvp

        spec = _xla_cost.instrument(spec, f"spec_k{k}")
        self._programs[key] = spec
        return spec

    # --- intake -------------------------------------------------------------
    def submit(self, input_ids, max_new_tokens=32, eos_token_id=None,
               request_id=None, tenant_id=None,
               priority_class=None, deadline=None,
               prebilled_tokens=0) -> RequestHandle:
        """Enqueue one sequence; returns its `RequestHandle`.  Raises
        ValueError when the request can never fit (prompt+max_new over
        the engine's per-sequence or pool capacity) — feasibility is
        checked at the door so the scheduler never deadlocks on an
        unservable request.  `tenant_id` names who the tenant ledger
        bills for this sequence's tokens/slot-time/page-seconds
        (ISSUE 16; None books under `anon`); `priority_class` orders
        admission and preemption (ISSUE 18; None → the default class);
        `deadline` (absolute monotonic) lets admission shed a request
        whose budget expired while queued with an honest
        `deadline_exceeded` instead of prefilling dead work;
        `prebilled_tokens` marks the first N accepted tokens as
        already billed by a prior replica (ISSUE 20 mid-stream resume
        — the decode books must conserve across the failover)."""
        seq = Sequence(input_ids, max_new_tokens,
                       eos_token_id=eos_token_id, request_id=request_id,
                       tenant_id=tenant_id, priority_class=priority_class,
                       deadline=deadline,
                       prebilled_tokens=prebilled_tokens)
        need = -(-(seq.prompt.size + seq.max_new_tokens)
                 // self.config.page_size)
        if need > self.pool.capacity:
            raise ValueError(
                f"request needs {need} pages, pool holds "
                f"{self.pool.capacity}")
        handle = RequestHandle(seq)
        seq.handle = handle
        if self._timeline_cap > 0:
            tl = RequestTimeline(seq.request_id, clock=self._clock,
                                 token_cap=self._timeline_cap)
            tl.event("submitted", prompt_tokens=int(seq.prompt.size),
                     max_new_tokens=int(seq.max_new_tokens))
            seq.timeline = tl
        # register BEFORE the scheduler can see the sequence: with the
        # loop thread running, a short request can be admitted,
        # finished, and its handle popped before submit() returns — a
        # post-hoc insert would leave a stale entry in _handles forever
        with self._lock:
            self._handles[seq.request_id] = handle
            if seq.timeline is not None:
                # the timeline map is a bounded LRU that OUTLIVES the
                # handle: /debug/requests/<id> answers for completed
                # requests too, until _TIMELINE_LRU newer ones arrive
                self._timelines.pop(seq.request_id, None)
                self._timelines[seq.request_id] = seq.timeline
                while len(self._timelines) > _TIMELINE_LRU:
                    # evict the oldest COMPLETED request first: a
                    # still-streaming request must stay debuggable
                    # exactly while its stall is happening (surge can
                    # push >128 submissions past a live stream).  All
                    # live (pathological) → the bound still wins.
                    victim = next(
                        (rid for rid in self._timelines
                         if rid not in self._handles), None)
                    if victim is None:
                        victim = next(iter(self._timelines))
                    self._timelines.pop(victim)
        try:
            self.scheduler.submit(seq)  # validates vs max_pages_per_seq
        except Exception:
            with self._lock:
                self._handles.pop(seq.request_id, None)
                # a refused request must not occupy a timeline slot (or
                # answer /debug/requests with a ghost 'submitted' row)
                self._timelines.pop(seq.request_id, None)
            raise
        _metrics.inc("engine.sequences", event="submitted")
        with self._work:
            self._work.notify_all()
        return handle

    def cancel(self, request_id) -> bool:
        """Abandon a sequence (client gone / explicit cancel): its
        handle completes as cancelled now; slot and pages return to the
        pool at the next schedule()."""
        ok = self.scheduler.cancel(request_id)
        if ok:
            _metrics.inc("engine.sequences", event="cancelled")
            with self._lock:
                handle = self._handles.pop(request_id, None)
            if handle is not None:
                handle._finish("cancelled")
            with self._work:
                self._work.notify_all()
        return ok

    # --- the engine step ----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: schedule -> prefill admissions ->
        ragged decode chunk -> detokenize/deliver.  Returns True when
        any work happened."""
        with self._lock:
            # spec mode writes up to spec_tokens+1 cache positions per
            # pass — the scheduler must provision pages for the whole
            # pass, not just the committed prefix
            chunk = (self.config.spec_tokens + 1 if self._draft
                     else self.config.decode_chunk)
            with _trace.span("engine.schedule", cat="engine"):
                out = self.scheduler.schedule(chunk)
            for seq in out.evicted:
                _metrics.inc("engine.sequences", event="evicted")
            for seq in out.finished:
                # released this schedule (completed earlier, or
                # cancelled while waiting/running): close the handle
                # and drop the engine's reference — a long-running
                # server must not accumulate one handle per cancelled
                # request
                self._handles.pop(seq.request_id, None)
                if seq.handle is not None:
                    seq.handle._finish(seq.finish_reason or "finished")
            did = bool(out.finished or out.evicted)
            for seq in out.prefills:
                self._prefill(seq)
                did = True
            running = [s for s in out.running
                       if not s.done and s.slot is not None]
            if running:
                if self._draft is not None:
                    self._spec_decode(running)
                else:
                    self._decode(running)
                did = True
            # free completed sequences' slots/pages NOW, not at the
            # next schedule — a drained engine must hold zero pages
            self.scheduler.release_finished()
            if did:
                self.steps += 1
            self._publish_gauges()
        return did

    def _bucket(self, s0: int) -> int:
        b = self.config.prefill_bucket
        return -(-s0 // b) * b

    def _prefill(self, seq: Sequence) -> None:  # pt-lint: ok[PT101,PT102] (step holds _lock)
        prompt = seq.resume_prompt()
        s0 = prompt.size
        shared = int(seq.shared_len or 0)
        if seq.timeline is not None:
            seq.timeline.event("prefill_start", tokens=s0,
                               shared=shared,
                               resumed=bool(seq.evictions))
        with _trace.span("engine.prefill", cat="engine",
                         request=seq.request_id, tokens=s0,
                         shared=shared, pages=len(seq.pages)):
            if shared > 0:
                t0, kbufs, vbufs, start = self._warm_prefill(
                    seq, prompt, shared)
            else:
                t0, kbufs, vbufs, start = self._cold_prefill(
                    seq, prompt)
            self._commit_prefix(seq, kbufs, vbufs, start)
            seq.length = s0
            seq.last_token = t0
        if seq.timeline is not None:
            seq.timeline.event("prefill_end", tokens=s0)
        if self._prefix is not None:
            if seq.cache_state in ("hit", "partial"):
                self._prefix_hits += 1
                _metrics.inc("engine.prefix_cache", event="hit")
            else:
                self._prefix_misses += 1
                _metrics.inc("engine.prefix_cache", event="miss")
            self._prefix_tokens_saved += shared
            self._prefix_tokens_total += s0
        if self.tenant_ledger is not None:
            # attribute prefill work — and the prefix cache's savings —
            # to the tenant (ISSUE 16): `shared` tokens came off cached
            # pages instead of running the model.  A recompute resume
            # bills its replayed tail honestly as computed work.
            self.tenant_ledger.record_prefill(
                seq.tenant_id, s0 - shared, saved=shared)
        _metrics.inc("engine.sequences", event="admitted")
        self._accept(seq, t0)

    def _cold_prefill(self, seq, prompt):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        """Dense prefill from token 0 (no cached prefix): the PR 8
        path.  Returns (first_token, k_bufs, v_bufs, pad_start) — the
        dense buffers feed `_commit_prefix` (prompt token t sits at
        buffer offset pad_start + t)."""
        s0 = prompt.size
        sb = self._bucket(s0)
        start = sb - s0
        quant = self.config.kv_precision == "int8"
        ids = np.zeros((1, sb), np.int32)
        ids[0, start:] = prompt
        prefill = self._prefill_program(sb)
        tok, kbufs, vbufs = prefill(
            self._params, self._buffers, jnp.asarray(ids),
            jnp.asarray([start], jnp.int32))
        ps = self.config.page_size
        npb = -(-sb // ps)
        pages = np.zeros((npb,), np.int32)
        n_real = min(len(seq.pages), npb)
        pages[:n_real] = seq.pages[:n_real]
        pages_j = jnp.asarray(pages)
        start_j = jnp.asarray(start, jnp.int32)
        pack = self._pack_program(sb)
        if quant:
            (self._k_pools, self._v_pools, self._k_scales,
             self._v_scales) = pack(
                self._k_pools, self._v_pools, self._k_scales,
                self._v_scales, kbufs, vbufs, pages_j, start_j)
        else:
            self._k_pools, self._v_pools = pack(
                self._k_pools, self._v_pools, kbufs, vbufs,
                pages_j, start_j)
        if self._draft is not None:
            # the draft re-prefills the same bucket into its own
            # pools (same page ids) so proposals continue from the
            # full prompt context
            dprefill = self._prefill_program(sb, "draft")
            _, dkb, dvb = dprefill(
                self._draft["params"], self._draft["buffers"],
                jnp.asarray(ids), jnp.asarray([start], jnp.int32))
            dpack = self._pack_program(sb, "draft")
            self._draft["k_pools"], self._draft["v_pools"] = dpack(
                self._draft["k_pools"], self._draft["v_pools"],
                dkb, dvb, pages_j, start_j)
        return int(np.asarray(jax.device_get(tok))[0]), kbufs, vbufs, \
            start

    @staticmethod
    def _prefix_bucket(n_pages: int) -> int:
        """Prefix page capacity bucket: next power of two.  Cached
        prefix lengths vary per hit; bucketing bounds the compiled
        (sb, npp) shape set — the PT402 recompile-hazard budget on
        `gpt_cached_prefill_step` exists to catch a per-length shape
        leak here."""
        npp = 1
        while npp < n_pages:
            npp *= 2
        return npp

    def _warm_prefill(self, seq, prompt, shared):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        """Prefill ONLY the tail [shared, s0): the cached prefix pages
        are already in the sequence's table (refcounted shares), so the
        model processes s0 - shared tokens instead of s0 — the TTFT win
        the bench gates.  The tail's K/V packs into the sequence's
        PRIVATE tail pages (the boundary page is never shared: the
        scheduler caps sharing at the last full page before s0), so no
        shared page is ever written."""
        cfg = self.config
        ps = cfg.page_size
        tail = prompt[shared:]
        sb = self._bucket(tail.size)
        start = sb - tail.size
        npa = shared // ps
        npp = self._prefix_bucket(npa)
        ids = np.zeros((1, sb), np.int32)
        ids[0, start:] = tail
        ids_j = jnp.asarray(ids)
        start_j = jnp.asarray([start], jnp.int32)
        plen = jnp.asarray(shared, jnp.int32)
        quant = cfg.kv_precision == "int8"
        pages = np.zeros((npp,), np.int32)
        pages[:npa] = seq.pages[:npa]
        pages_j = jnp.asarray(pages)
        cpre = self._cached_prefill_program(sb, npp)
        if quant:
            ek, ev = self._sidecar_prefix(seq, npa, npp)
            tok, kbufs, vbufs = cpre(self._params, self._buffers,
                                     ids_j, start_j, plen, ek, ev)
        else:
            tok, kbufs, vbufs = cpre(self._params, self._buffers,
                                     ids_j, start_j, pages_j, plen,
                                     self._k_pools, self._v_pools)
        # pack the tail into the PRIVATE tail pages; in the returned
        # buffers prompt token t sits at offset start + t (the write
        # landed at [shared, shared+sb), tail token j at shared+start+j)
        npb = -(-sb // ps)
        tpages = np.zeros((npb,), np.int32)
        n_tail = max(0, min(len(seq.pages) - npa, npb))
        tpages[:n_tail] = seq.pages[npa:npa + n_tail]
        tpages_j = jnp.asarray(tpages)
        pk_start = jnp.asarray(shared + start, jnp.int32)
        pack = self._pack_program(sb)
        if quant:
            (self._k_pools, self._v_pools, self._k_scales,
             self._v_scales) = pack(
                self._k_pools, self._v_pools, self._k_scales,
                self._v_scales, kbufs, vbufs, tpages_j, pk_start)
        else:
            self._k_pools, self._v_pools = pack(
                self._k_pools, self._v_pools, kbufs, vbufs,
                tpages_j, pk_start)
        if self._draft is not None:
            # warm-prefill the draft's tail over ITS pools (exact
            # precision, same page ids): the cached prefix pages hold
            # the donor's draft K/V — a pure function of the prefix
            # tokens, so they are this prompt's draft prefix too
            dcpre = self._cached_prefill_program(sb, npp, "draft")
            _, dkb, dvb = dcpre(
                self._draft["params"], self._draft["buffers"], ids_j,
                start_j, pages_j, plen, self._draft["k_pools"],
                self._draft["v_pools"])
            dpack = self._pack_program(sb, "draft")
            self._draft["k_pools"], self._draft["v_pools"] = dpack(
                self._draft["k_pools"], self._draft["v_pools"],
                dkb, dvb, tpages_j, pk_start)
        # commit offset contract (_commit_prefix): prompt token t sits
        # at buffer offset start + t — the fresh span landed at
        # [shared, shared+sb), so tail token j (= prompt token
        # shared+j) is at shared + start + j = start + (shared+j).
        # Returning shared+start here would shift every sidecar slice
        # one whole prefix past the real tokens.
        return int(np.asarray(jax.device_get(tok))[0]), kbufs, vbufs, \
            start

    def _sidecar_prefix(self, seq, npa, npp):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        """int8-KV tier: stack the matched radix nodes' commit-time
        EXACT page copies into the warm program's per-layer prefix
        buffers ([npp, hkv, ps, d], zero-padded past npa)."""
        zero = jnp.zeros((self._hkv, self.config.page_size, self._hd),
                         self._dtype)
        ek, ev = [], []
        for li in range(self._layers):
            ks, vs = [], []
            for i in range(npa):
                ex = seq.shared_nodes[i].exact
                if ex is None:
                    raise RuntimeError(
                        "prefix-cache node without an exact sidecar "
                        "under kv_precision=int8 (commit-path bug)")
                ks.append(ex[li][0])
                vs.append(ex[li][1])
            pad = [zero] * (npp - npa)
            ek.append(jnp.stack(ks + pad))
            ev.append(jnp.stack(vs + pad))
        return ek, ev

    def _commit_prefix(self, seq, kbufs, vbufs, start):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        """Register the ORIGINAL prompt's full pages in the radix index
        (the partial tail page stays private — it is still written by
        decode).  `start` is the buffer offset of prompt token 0 in the
        just-returned dense buffers: in BOTH the cold and warm cases
        prompt token t sits at `start + t`, which is where the int8
        sidecar's exact page copies are sliced from."""
        if self._prefix is None:
            return
        ps = self.config.page_size
        n_full = min(int(seq.prompt.size) // ps, len(seq.pages))
        if n_full <= 0:
            return
        exact = None
        if self.config.kv_precision == "int8":
            shared_chunks = int(seq.shared_len or 0) // ps
            exact = []
            for i in range(n_full):
                if i < shared_chunks:
                    # node already exists (matched at admission);
                    # insert never reads this slot
                    exact.append(None)
                    continue
                lo = start + i * ps
                exact.append([
                    (kbufs[li][0, :, lo:lo + ps, :],
                     vbufs[li][0, :, lo:lo + ps, :])
                    for li in range(self._layers)])
        self._prefix.insert(seq.prompt[:n_full * ps],
                            seq.pages[:n_full], exact=exact)

    def _batch_arrays(self, running):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        s_, p_ = self.config.max_slots, self.max_pages_per_seq
        tok = np.zeros((s_,), np.int32)
        pt = np.zeros((s_, p_), np.int32)
        lengths = np.zeros((s_,), np.int32)
        for seq in running:
            tok[seq.slot] = seq.last_token
            pt[seq.slot, :len(seq.pages)] = seq.pages
            lengths[seq.slot] = seq.length
        return jnp.asarray(tok), jnp.asarray(pt), jnp.asarray(lengths)

    def _scales_args(self):  # pt-lint: ok[PT101,PT102] (step holds _lock)
        if self._k_scales is None:
            return [], []
        return self._k_scales, self._v_scales

    def _decode(self, running) -> None:  # pt-lint: ok[PT101,PT102] (step holds _lock)
        cfg = self.config
        t_step = time.perf_counter()
        tok, pt, lengths = self._batch_arrays(running)
        # ALWAYS dispatch the configured chunk: shrinking the scan to
        # the batch's max remaining would compile one program per
        # distinct tail length — a compile per shape costs far more
        # than the few discarded tail tokens, and a single decode
        # program is the fixed-compiled-shape contract
        n = cfg.decode_chunk
        decode = self._decode_program(n)
        ks, vs = self._scales_args()
        with _trace.span("engine.decode", cat="engine", batch=len(running),
                         chunk=n, occupancy=len(running) / cfg.max_slots):
            toks, self._k_pools, self._v_pools, ks, vs = decode(
                self._params, self._buffers, self._k_pools,
                self._v_pools, ks, vs, tok, pt, lengths)
            if self._k_scales is not None:
                self._k_scales, self._v_scales = ks, vs
        with _trace.span("engine.detokenize", cat="engine",
                         batch=len(running), chunk=n):
            toks = np.asarray(jax.device_get(toks))
            for seq in running:
                row = toks[seq.slot]
                for j in range(n):
                    if seq.done:
                        break  # mid-chunk finish: later tokens are the
                        # frozen-slot continuation, not output
                    self._accept(seq, int(row[j]))
                seq.length += n
                seq.last_token = int(row[n - 1])
        self._bill_decode_slots(running, t_step)

    def _spec_decode(self, running) -> None:  # pt-lint: ok[PT101,PT102] (step holds _lock)
        cfg = self.config
        k = cfg.spec_tokens
        t_step = time.perf_counter()
        tok, pt, lengths = self._batch_arrays(running)
        # per-slot lifetime cap (prompt+max_new cache positions): rows
        # of the pass at or past it are masked to the scratch page
        # inside the program (free slots stay at 0 = fully masked)
        limits = np.zeros((cfg.max_slots,), np.int32)
        for seq in running:
            limits[seq.slot] = seq.prompt.size + seq.max_new_tokens
        spec = self._spec_program(k)
        ks, vs = self._scales_args()
        d = self._draft
        with _trace.span("engine.decode", cat="engine",
                         batch=len(running), chunk=k + 1, spec=True,
                         occupancy=len(running) / cfg.max_slots):
            (g, counts, self._k_pools, self._v_pools, ks, vs,
             d["k_pools"], d["v_pools"]) = spec(
                self._params, self._buffers, d["params"], d["buffers"],
                self._k_pools, self._v_pools, ks, vs,
                d["k_pools"], d["v_pools"], tok, pt, lengths,
                jnp.asarray(limits))
            if self._k_scales is not None:
                self._k_scales, self._v_scales = ks, vs
        with _trace.span("engine.detokenize", cat="engine",
                         batch=len(running), chunk=k + 1):
            g = np.asarray(jax.device_get(g))
            counts = np.asarray(jax.device_get(counts))
            for seq in running:
                row = g[seq.slot]
                cnt = int(counts[seq.slot])
                # cnt-1 draft proposals were accepted; the rest of the
                # pass's k proposals were rejected (their cache slots
                # get overwritten before any later step attends them)
                _metrics.inc("engine.spec_decode", cnt - 1,
                             result="accepted")
                _metrics.inc("engine.spec_decode", k - (cnt - 1),
                             result="rejected")
                for j in range(cnt):
                    if seq.done:
                        break  # mid-pass finish (eos): later tokens are
                        # the frozen continuation, not output
                    self._accept(seq, int(row[j]))
                seq.length += cnt
                seq.last_token = int(row[cnt - 1])
        self._bill_decode_slots(running, t_step)

    def _bill_decode_slots(self, running, t_step) -> None:
        """Decode-slot occupancy billing (ISSUE 16): every sequence in
        the pass occupied one batch slot for the step's wall time —
        THE contended capacity unit (max_slots), so a tenant holding
        slots with long sequences shows up even at a low token rate.
        The same charge feeds the scheduler's quota/fairness meter
        (ISSUE 18) — QoS prices in the unit the ledger bills."""
        if not running:
            return
        step_ms = (time.perf_counter() - t_step) * 1e3
        for seq in running:
            if self.tenant_ledger is not None:
                self.tenant_ledger.record_decode_slot_ms(
                    seq.tenant_id, step_ms)
            self.scheduler.note_decode_slot_ms(seq.tenant_id, step_ms)

    def _accept(self, seq: Sequence, tok: int) -> None:
        """One generated token passes the host: record, deliver,
        finish on eos / length (mirrors generate()'s freezing: the eos
        itself is emitted, nothing after it)."""
        seq.tokens.append(int(tok))
        if seq.timeline is not None:
            seq.timeline.token()
        if len(seq.tokens) <= seq.prebilled_tokens:
            # resume verify token (ISSUE 20): the dead replica already
            # billed this position — re-deriving it must not double a
            # tenant's decode book (neither branch below runs, so
            # engine.tokens and the per-tenant total stay in lockstep)
            pass
        elif self.tenant_ledger is not None:
            # the ledger incs engine.tokens INSIDE its lock so the
            # counter and per-tenant decode totals move atomically (a
            # concurrent snapshot can never see them skewed)
            self.tenant_ledger.record_decode(seq.tenant_id)
        else:
            _metrics.inc("engine.tokens")
        if seq.handle is not None:
            seq.handle._push(tok)
        if seq.eos_token_id is not None and int(tok) == seq.eos_token_id:
            self._finish(seq, "eos")
        elif len(seq.tokens) >= seq.max_new_tokens:
            self._finish(seq, "length")

    def _finish(self, seq: Sequence, reason: str) -> None:
        if seq.timeline is not None:
            seq.timeline.event("finished", reason=reason,
                               generated=len(seq.tokens))
        self.scheduler.finish(seq, reason)
        # release the slot/pages BEFORE the handle signals completion:
        # a client (or test) that observes the finished stream must
        # never find the sequence's pages still held — the end-of-step
        # release would otherwise race the handler thread by however
        # long the GIL delays the step's tail
        self.scheduler.release_finished()
        _metrics.inc("engine.sequences", event="completed")
        if seq.handle is not None:
            seq.handle._finish(reason)
        with self._lock:
            self._handles.pop(seq.request_id, None)

    def _publish_gauges(self) -> None:  # pt-lint: ok[PT102] (_prefix set once at construction, never rebound)
        st = self.scheduler.stats()
        _metrics.set_gauge("engine.active_sequences", st["running"])
        _metrics.set_gauge("engine.waiting_sequences", st["waiting"])
        _metrics.set_gauge("engine.batch_occupancy", st["occupancy"])
        _metrics.set_gauge("engine.page_utilization",
                           self.pool.utilization())
        if self._prefix is not None:
            total = self._prefix_hits + self._prefix_misses
            _metrics.set_gauge("engine.prefix_cached_tokens",
                               self._prefix.cached_tokens)
            _metrics.set_gauge("engine.prefix_cache_hit_rate",
                               (self._prefix_hits / total) if total
                               else 0.0)

    # --- maintenance --------------------------------------------------------
    def defrag(self) -> int:
        """Compact live pages to the densest pool prefix: apply the
        allocator's moves to the device pools and every live page
        table.  Returns the number of pages moved."""
        with self._lock:
            moves = self.pool.defrag()
            if not moves:
                return 0
            self.decisions.record(
                "defrag", moves=len(moves),
                pressure=round(self.pool.utilization(), 4))
            # ascending-dst order is overwrite-safe: src > dst always,
            # and every src exceeds all earlier dsts
            for src, dst in sorted(moves.items(), key=lambda kv: kv[1]):
                self._k_pools = [p.at[dst].set(p[src])
                                 for p in self._k_pools]
                self._v_pools = [p.at[dst].set(p[src])
                                 for p in self._v_pools]
                if self._k_scales is not None:
                    self._k_scales = [s.at[dst].set(s[src])
                                      for s in self._k_scales]
                    self._v_scales = [s.at[dst].set(s[src])
                                      for s in self._v_scales]
                if self._draft is not None:
                    d = self._draft
                    d["k_pools"] = [p.at[dst].set(p[src])
                                    for p in d["k_pools"]]
                    d["v_pools"] = [p.at[dst].set(p[src])
                                    for p in d["v_pools"]]
            for seq in self.scheduler.running_seqs():
                seq.pages = [moves.get(p, p) for p in seq.pages]
            if self._prefix is not None:
                self._prefix.apply_moves(moves)
        return len(moves)

    def clear_prefix_cache(self) -> int:
        """Drop every prefix-cache reference (pages shared with live
        sequences stay live under the sequences' own refs).  Returns
        the number of cache pages released — after a full drain plus a
        clear, `pool.used_pages` must be exactly 0 (the chaos leak
        assertion)."""
        with self._lock:
            if self._prefix is None:
                return 0
            return self._prefix.clear()

    def prefix_cache_stats(self) -> dict:  # pt-lint: ok[PT102] (_prefix set once at construction; counters are monotonic snapshots)
        """Hit/miss/saved-token ledger + radix index size — rides
        `engine.stats()` into /ready and /debug/telemetry."""
        hits, misses = self._prefix_hits, self._prefix_misses
        total = hits + misses
        st = {
            "enabled": self._prefix is not None,
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
            "prefill_tokens_saved": self._prefix_tokens_saved,
            "prefill_tokens_total": self._prefix_tokens_total,
            "tokens_saved_frac":
                (self._prefix_tokens_saved
                 / max(1, self._prefix_tokens_total)),
        }
        if self._prefix is not None:
            st.update(self._prefix.stats())
        return st

    # --- per-token latency attribution (ISSUE 15) ---------------------------
    def request_debug(self, request_id):
        """The answer to "why was this token slow": the request's
        timeline (events, decimated token stamps, top inter-token
        gaps), each gap annotated with the scheduler decisions that
        landed INSIDE it (admits of other sequences, recompute
        evictions, prefix reclaims, defrags — with seq ids and the
        page pressure at decision time) plus a human-readable `cause`
        line.  None for unknown / aged-out ids.  Works for completed
        requests until `_TIMELINE_LRU` newer submissions age them
        out."""
        with self._lock:
            tl = self._timelines.get(request_id)
        if tl is None:
            return None
        d = tl.describe()
        for gap in d["gaps"]:
            evs = self.decisions.window(gap["t_start"], gap["t_end"],
                                        pad=0.005)
            gap["events"] = evs
            causes = []
            for ev in evs:
                who = ev.get("request_id") or ev.get("for_request")
                if ev["kind"] == "evict_recompute" \
                        and ev.get("request_id") == request_id:
                    causes.append(
                        f"evicted (recompute) for "
                        f"{ev.get('for_request')}, pool at "
                        f"{ev.get('pressure', 0):.0%}")
                elif ev["kind"] == "admit" and who != request_id:
                    causes.append(
                        f"co-scheduled {ev.get('cache_state', 'cold')} "
                        f"prefill of {who}, pool at "
                        f"{ev.get('pressure', 0):.0%}")
                elif who != request_id:
                    causes.append(
                        f"co-scheduled {ev['kind']} "
                        f"({who or 'pool'}), pool at "
                        f"{ev.get('pressure', 0):.0%}")
                else:
                    causes.append(
                        f"{ev['kind']} of this request, pool at "
                        f"{ev.get('pressure', 0):.0%}")
            gap["cause"] = "; ".join(causes) if causes else None
        d["decision_ring_tail"] = self.decisions.events(limit=32)
        return d

    def recent_timelines(self, n=8) -> list:
        """Bounded per-request timeline summaries, newest last — what
        /debug/telemetry and the exporter dumps embed (full detail
        stays behind /debug/requests/<id>)."""
        with self._lock:
            tls = list(self._timelines.values())[-int(n):]
        return [tl.summary() for tl in tls]

    # --- loop / lifecycle ---------------------------------------------------
    def start(self):
        """Run the engine loop on a daemon thread (the serving mode);
        `step()` remains callable inline for tests."""
        with self._lock:
            if self._thread is not None:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="paddle-tpu-engine")
            self._thread.start()
        return self

    def _loop(self):
        # _running is a stop flag: a stale read costs one extra step;
        # taking the lock here would serialize the loop against submit()
        while self._running:  # pt-lint: ok[PT102]
            if not self.step():
                with self._work:
                    # pt-lint: ok[PT504] (wakeup re-check: _running/scheduler are OWNED by _lock; reading them under the _work cv is the standard missed-notify guard — a stale read costs one 50ms wait)
                    if self._running and not self.scheduler.has_work():
                        self._work.wait(timeout=0.05)

    def stop(self, timeout: float = 10.0):
        with self._lock:
            self._running = False
            thread = self._thread
            self._thread = None
        with self._work:
            self._work.notify_all()
        if thread is not None:
            thread.join(timeout=timeout)

    # --- convenience (tests / bench / equivalence) --------------------------
    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 timeout: float = 300.0):
        """Submit every prompt and run the engine to completion
        (inline when the loop thread is not running).  Returns a list
        of int32 [s0_i + n_generated_i] arrays — `generate()`-shaped
        output for direct equivalence checks."""
        handles = [self.submit(p, max_new_tokens,
                               eos_token_id=eos_token_id)
                   for p in prompts]
        # _thread is set-once before any submit in the loop-thread
        # mode; inline callers never race it
        if self._thread is None:  # pt-lint: ok[PT102]
            idle = 0
            while any(not h.done.is_set() for h in handles):
                if self.step():
                    idle = 0
                else:
                    idle += 1
                    if idle > 1000:
                        raise RuntimeError(
                            "engine made no progress (scheduler stuck)")
        return [h.result(timeout=timeout) for h in handles]

    def stats(self) -> dict:
        st = self.scheduler.stats()
        st["pages"] = self.pool.stats()
        cfg = self.config
        # the active quantized-decode tiers ride the stats dict into
        # /health and /ready (serving.py embeds engine.stats() there)
        st["weight_precision"] = cfg.weight_precision or "full"
        st["kv_precision"] = cfg.kv_precision or "full"
        # pt-lint: ok[PT102] (None-check of the set-once _draft binding)
        st["spec_tokens"] = cfg.spec_tokens if self._draft else 0
        st["page_bytes"] = self._page_bytes()
        st["prefix_cache"] = self.prefix_cache_stats()
        # monotonic int snapshot for telemetry; a stale read is a fine
        # answer to "how many steps so far"
        st["steps"] = self.steps  # pt-lint: ok[PT102]
        return st
