"""paddle_tpu.inference.engine — continuous-batching inference engine
with a paged KV cache (docs/INFERENCE.md).

  * `paging`     — host page-pool allocator (refcounted alloc/share/
                   free, defrag; prefix sharing rides the refcounts).
  * `prefix`     — radix prefix index: page-aligned committed prompt
                   prefixes -> physical pages (LRU idle eviction).
  * `scheduler`  — slot/admission/eviction policy at one fixed
                   compiled batch shape (injectable clock); admission
                   shares the longest cached prefix into the table.
  * `engine`     — the `InferenceEngine`: bucketed dense prefill (cold)
                   or cached tail prefill (warm), pack-to-pages, ragged
                   paged decode steps (`ops/pallas/paged_attention`),
                   request handles.

Serving wires an engine behind `POST /generate`
(`inference/serving.py`), fed through the existing
`AdmissionController` so shedding happens only past true saturation.
"""
from __future__ import annotations

from .engine import EngineConfig, InferenceEngine, RequestHandle  # noqa: F401
from .paging import OutOfPages, PagePool, SCRATCH_PAGE  # noqa: F401
from .prefix import PrefixIndex  # noqa: F401
from .scheduler import Scheduler, SchedulerOutput, Sequence  # noqa: F401

__all__ = [
    "EngineConfig", "InferenceEngine", "RequestHandle",
    "PagePool", "OutOfPages", "SCRATCH_PAGE", "PrefixIndex",
    "Scheduler", "SchedulerOutput", "Sequence",
]
