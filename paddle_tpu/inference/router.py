"""Admission-aware HTTP router over a fleet of InferenceServer replicas.

One process cannot serve millions of users: PR 8's continuous-batching
engine still sat behind a single `InferenceServer`, so one preemption
took the whole serving plane down.  The `Router` is the deployment
story (ROADMAP item 5): N replicas — typically launched and supervised
by `inference.fleet.ReplicaFleet`, one per chip slice — behind a thin
stdlib HTTP proxy that routes on the *admission signals the replicas
already export* and survives replicas dying under it.

Routing (docs/SERVING.md):
  * **least-loaded pick** — a probe loop polls every replica's
    `GET /ready` (which now carries `inflight`/`queued`/
    `admission_limit` and the engine's `batch_occupancy`/
    `waiting_sequences`, ISSUE 9 satellite); `/predict` goes to the
    replica with the lowest (inflight+queued)/limit, `/generate` to the
    emptiest decode engine.  Router-side in-flight counts are added so
    bursts between probes don't herd onto one replica.
  * **failover** — a replica that dies mid-request (connection error),
    trips its `CircuitBreaker` (resilience.retry reuse), or misses
    `heartbeat_miss_k` heartbeats is skipped/ejected; in-flight
    non-streamed requests transparently retry on a healthy replica
    under the SAME `X-Request-Id` (ISSUE 7 discipline).  Streamed
    `/generate` requests fail over freely while ZERO tokens have been
    delivered; once tokens ARE delivered, a replica loss triggers a
    deterministic mid-stream RESUME (ISSUE 20): the router resubmits
    `prompt + delivered[:-1]` to another replica (valid by the greedy
    determinism contract), requires the leg's first token to reproduce
    `delivered[-1]` (divergence check, token swallowed, billed
    nowhere), then keeps streaming — zero replay, same request id,
    `"resumed": n` on the final record.  Resume is bounded
    (`stream_resume_max` legs), deadline-aware and class-gated; any
    refusal or divergence falls back LOUDLY to one clean `interrupted`
    record carrying the resumable `output_ids` prefix — never replayed
    tokens (`InferenceClient` raises `StreamInterrupted`, or resumes
    client-side itself with `resume=True`).
  * **drain-aware** — `mark_draining()` stops routing BEFORE the
    replica's own drain begins (the fleet calls it ahead of SIGTERM, so
    clients never see a thundering herd of 503s); a replica whose
    readiness reports `draining` is likewise taken out of rotation.
  * **edge admission** — ONE fleet-level `AdmissionController` (its
    capacity tracks the live sum of routable replica limits via
    `set_capacity`) sheds once, at the edge, with an honest
    `Retry-After`; `no_replicas` sheds map to 503.

Telemetry: `router.replicas{state=up|draining|ejected|down}` and
`router.capacity{endpoint}` gauges (live routable capacity, ISSUE 14),
`router.failovers` / `router.ejections` / `router.readmissions` and
`router.requests{endpoint,status}` counters (attach() schema),
`router.stream_resumes{outcome=ok|diverged|exhausted}` counters with
the `router.resume_gap_ms` histogram attributing the client-visible
resume seam, and `router.request`/`router.forward` spans carrying
request identity.
The router also keeps a fleet-level `SLOTracker` (`router.slo`) fed
from every finished edge request — sheds and unsaved failures burn
budget here even when each replica's own ledger is clean; its burn
rate is the `inference.autoscaler.Autoscaler`'s primary scale signal.
Fault points: `router.forward` fires per forward attempt,
`router.stream_read` per streamed line read (severs a stream
mid-flight deterministically), `router.resume_verify` at the
divergence check (forces the loud fallback) — all chaos-drivable.

Prefix-affinity routing (ISSUE 13, docs/SERVING.md): /generate
requests may carry an `X-Prefix-Fingerprint` header (the client's
cheap hash of the first N page-aligned prompt tokens; the router
computes its own from the parsed prompt when absent).  A bounded LRU
fingerprint->replica map remembers where each prefix last landed, and
the pick PREFERS the affine replica when its load is within
`affinity_slack` of the least-loaded candidate — repeat tenants land
where their prefix cache lives, without ever overriding drain/eject
state (affine picks are drawn from the routable set only) and without
letting affinity pile load on one replica (the slack bound).  The
fingerprint is routing metadata ONLY — the engine's radix index
matches real token values, so a poisoned header degrades to a cache
miss, never a wrong-token stream.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_HEARTBEAT_MISS_K   probes/beats missed before ejection (3)
  PADDLE_TPU_FAILOVER_RETRIES   extra replicas tried per request    (2)
  PADDLE_TPU_ROUTER_AFFINITY_SLACK  affine-pick load slack       (0.25)
  PADDLE_TPU_STREAM_RESUME_MAX      mid-stream resume legs/stream  (2)
  PADDLE_TPU_STREAM_RESUME_CLASSES  classes served by resume     (all)

Transport and clock are injectable — unit tests drive the whole state
machine with fake replicas and no sockets (tests/test_router.py).
"""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tenant_ledger as _tledger
from ..observability import timeseries as _ts
from ..observability import trace as _trace
from ..observability.slo import SLOTracker
from ..resilience.overload import AdmissionController, ShedError, _env_num
from ..resilience.retry import CircuitBreaker, CircuitOpenError
from . import qos as _qos
from .serving import _retry_after_header

__all__ = ["Router", "HTTPTransport", "ReplicaUnreachable"]

_REPLICA_STATES = ("up", "draining", "ejected", "down")

# the router's declared timeseries set (ISSUE 15): edge pressure and
# fleet capacity — the queue-growth derivatives the autoscaler's
# predictive signal is made of, visible on GET /debug/timeseries.
# Bare names sum their label variants (right for counters and for
# capacity); replica-count gauges are watched at their EXACT labeled
# keys — summing target+actual or up+down would double-count.
ROUTER_SERIES = (
    "router.requests", "router.capacity",
    "router.replicas{state=up}", "router.failovers",
    "router.stream_resumes",
    "serving.inflight", "serving.queue_depth",
    "autoscaler.replicas{state=actual}",
)


class ReplicaUnreachable(ConnectionError):
    """Transport-level failure talking to a replica (refused, reset,
    premature EOF): the failover trigger, as opposed to an HTTP status
    the replica deliberately sent."""


class _HTTPStream:
    """One open streamed response off a replica: status + headers up
    front, then an ndjson line iterator.  `close()` is idempotent and
    tears the TCP connection down (a client abandoning the proxy stream
    propagates as a dead socket the replica can notice)."""

    def __init__(self, conn, resp):
        self._conn = conn
        self._resp = resp
        self.status = resp.status
        self.headers = dict(resp.headers)

    def lines(self):
        for line in self._resp:
            yield line

    def read_body(self):
        return self._resp.read()

    def close(self):
        try:
            self._conn.close()
        except Exception:  # pt-lint: ok[PT005]
            pass           # (teardown best-effort: the socket may
            # already be gone — that is often WHY we are closing)


class HTTPTransport:
    """Default transport: stdlib http.client.  Connection-level
    failures (refused/reset/timeout on connect, dead socket mid-read)
    raise `ReplicaUnreachable`; HTTP statuses — including 4xx/5xx — are
    returned, not raised (the router decides what they mean)."""

    def _connect(self, address, timeout):
        u = urllib.parse.urlparse(address)
        return http.client.HTTPConnection(u.hostname, u.port,
                                          timeout=timeout)

    def request(self, address, method, path, body=None, headers=None,
                timeout=30.0):
        """Buffered exchange: returns (status, headers dict, body bytes)."""
        conn = self._connect(address, timeout)
        try:
            conn.request(method, path, body=body,
                         headers=dict(headers or {}))
            resp = conn.getresponse()
            return resp.status, dict(resp.headers), resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaUnreachable(
                f"{address}{path}: {type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def stream(self, address, path, body, headers=None, timeout=30.0):
        """Open a streamed POST; returns an `_HTTPStream` (caller owns
        `close()`).  Only the CONNECT + status-line phase raises
        `ReplicaUnreachable` here — mid-stream failures surface from
        the line iterator as OSError/HTTPException for the caller to
        classify against how much was already delivered."""
        conn = self._connect(address, timeout)
        try:
            conn.request("POST", path, body=body,
                         headers=dict(headers or {}))
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise ReplicaUnreachable(
                f"{address}{path}: {type(e).__name__}: {e}") from e
        return _HTTPStream(conn, resp)


class _Replica:
    """Router-side view of one replica.  All mutable fields are guarded
    by the Router's `_lock` (single coarse lock: the table is small and
    every transition must be atomic against the probe loop)."""

    __slots__ = ("id", "address", "breaker", "state", "signals",
                 "missed_heartbeats", "probe_failures", "inflight",
                 "generation", "draining_requested", "ever_up",
                 "ever_beat", "ever_forwarded")

    def __init__(self, rid, address, breaker):
        self.id = str(rid)
        self.address = str(address)
        self.breaker = breaker
        self.state = "down"          # probe promotes to "up"
        self.signals = {}            # last /ready payload
        self.missed_heartbeats = 0
        self.probe_failures = 0
        self.inflight = {"predict": 0, "generate": 0}
        self.generation = 0
        self.draining_requested = False
        self.ever_up = False         # first admission ≠ re-admission
        self.ever_beat = False       # heartbeats govern only after one
        self.ever_forwarded = False  # lifecycle first_routable_request

    def view(self):  # pt-lint: ok[PT102] (caller holds Router._lock)
        sig = self.signals
        return {
            "id": self.id, "address": self.address, "state": self.state,
            "breaker": self.breaker.state,
            "missed_heartbeats": self.missed_heartbeats,
            "probe_failures": self.probe_failures,
            "inflight": dict(self.inflight),
            "generation": self.generation,
            "signals": {k: sig.get(k) for k in
                        ("inflight", "queued", "admission_limit",
                         "engine") if k in sig},
        }


class Router:
    """Admission-aware reverse proxy over a replica fleet.  See the
    module docstring for semantics; `start()` returns immediately
    (daemon threads: HTTP accept loop + readiness/heartbeat probe
    loop), `shutdown()` drains the edge controller and closes the
    socket — replica lifecycle belongs to `ReplicaFleet`, not here."""

    # bounded fingerprint->replica map: enough for a large tenant
    # population, small enough that a hostile client cannot balloon
    # router memory by spraying fingerprints
    AFFINITY_CAP = 4096

    def __init__(self, host="127.0.0.1", port=0, replicas=None,
                 heartbeat_miss_k=None, failover_retries=None,
                 probe_interval=0.25, request_timeout=30.0,
                 max_inflight=None, queue_depth=None, transport=None,
                 heartbeats=None, clock=time.monotonic,
                 breaker_threshold=3, breaker_reset=2.0,
                 affinity_slack=None, stream_resume_max=None,
                 stream_resume_classes=None):
        if heartbeat_miss_k is None:
            heartbeat_miss_k = _env_num("PADDLE_TPU_HEARTBEAT_MISS_K",
                                        3, int)
        if failover_retries is None:
            failover_retries = _env_num("PADDLE_TPU_FAILOVER_RETRIES",
                                        2, int)
        if affinity_slack is None:
            affinity_slack = _env_num(
                "PADDLE_TPU_ROUTER_AFFINITY_SLACK", 0.25, float)
        if stream_resume_max is None:
            stream_resume_max = _env_num("PADDLE_TPU_STREAM_RESUME_MAX",
                                         2, int)
        self.heartbeat_miss_k = max(1, int(heartbeat_miss_k))
        self.failover_retries = max(0, int(failover_retries))
        # mid-stream failover (ISSUE 20): how many resume legs one
        # /generate stream may consume, and which QoS classes are worth
        # the resume re-prefill at all (unset = every class)
        self.stream_resume_max = max(0, int(stream_resume_max))
        self.stream_resume_classes = (
            _qos.resume_classes_from_env()
            if stream_resume_classes is None
            else frozenset(_qos.normalize_class(c)
                           for c in stream_resume_classes) - {None})
        self.affinity_slack = max(0.0, float(affinity_slack))
        self._affinity = OrderedDict()  # fingerprint -> rid (LRU)
        self.probe_interval = float(probe_interval)
        self.request_timeout = (None if request_timeout is None
                                else float(request_timeout))
        self.transport = transport or HTTPTransport()
        self.heartbeats = heartbeats  # callable -> iterable of live ids
        self.clock = clock
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset = float(breaker_reset)
        self._lock = threading.Lock()
        self._replicas: dict = {}     # rid -> _Replica (under _lock)
        # ONE fleet-level edge controller per endpoint class: shedding
        # happens once, here, with an honest Retry-After — capacities
        # re-track the live routable fleet on every probe pass
        self.admission = AdmissionController(
            max_inflight=max_inflight, queue_depth=queue_depth,
            name="router")
        self.gen_admission = AdmissionController(
            max_inflight=max_inflight, queue_depth=queue_depth,
            name="router.generate")
        # fleet-level SLO ledger (ISSUE 14): what the CLIENT-FACING
        # edge delivered — sheds and failed-over-into-errors consume
        # budget here even when every replica's own ledger is clean.
        # Its windowed burn rate is the autoscaler's primary signal.
        self.slo = SLOTracker(
            window_s=_env_num("PADDLE_TPU_SLO_WINDOW", 300.0, float),
            clock=clock)
        paid_avail = _env_num(
            "PADDLE_TPU_SLO_PAID_AVAILABILITY",
            _env_num("PADDLE_TPU_SLO_AVAILABILITY", 0.999, float),
            float)
        for ep, target in (("predict", 1000.0), ("generate", 30000.0)):
            latency_ms = _env_num(
                "PADDLE_TPU_SLO_LATENCY_MS" if ep == "predict"
                else "PADDLE_TPU_SLO_GENERATE_LATENCY_MS",
                target, float)
            self.slo.objective(
                ep, latency_target_ms=latency_ms,
                availability=_env_num("PADDLE_TPU_SLO_AVAILABILITY",
                                      0.999, float))
            # the paid tier's own promise (ISSUE 18): its burn rate is
            # what the autoscaler scales for — free/batch inherit the
            # endpoint objective (degrading them is the DESIGN under
            # surge, not a page)
            self.slo.objective(ep, latency_target_ms=latency_ms,
                               availability=paid_avail, cls="paid")
        # per-tenant metering at the EDGE (ISSUE 16): the router's own
        # book bills every request it answers — including sheds and
        # failed failovers a replica never saw, which is exactly what
        # replica-side books cannot capture.  Request counts here and
        # on replicas are per-HOP tallies (like router.requests vs
        # serving.requests); token/page fields bill engine-side only,
        # so the fleet merge of REPLICA books still conserves.
        self.tenant_ledger = _tledger.TenantLedger() \
            if _tledger.enabled() and _metrics.enabled() else None
        # time-dimension telemetry (ISSUE 15): sampled edge/capacity
        # series behind GET /debug/timeseries (rates + derivatives)
        self.timeseries = _ts.TimeSeriesSampler(names=ROUTER_SERIES,
                                                name="router")
        _ts.set_default_sampler(self.timeseries)
        # replica lifecycle plane (ISSUE 17): ReplicaFleet wires its
        # FleetLifecycle here so the probe loop can stamp
        # first_probe_up / first_routable_request and durably attach
        # each replica's own phase record.  None for a bare Router.
        self.lifecycle = None
        for rid, address in dict(replicas or {}).items():
            self.add_replica(rid, address)
        self._probe_stop = threading.Event()
        self._probe_thread = None
        self._serving = False
        self._shutdown_lock = threading.Lock()
        self._shutdown_done = False
        router = self

        class Handler(BaseHTTPRequestHandler):
            _rt_ctx = None

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code, obj, headers=()):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if self._rt_ctx is not None:
                    self.send_header("X-Request-Id",
                                     self._rt_ctx.request_id)
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    return self._json(200, {
                        "status": "ok", "role": "router",
                        "replicas": router.replica_summary()})
                if self.path == "/ready":
                    ready, reason = router.readiness()
                    body = {"status": "ready" if ready else "not_ready",
                            "reason": reason,
                            "routable": router.routable_count()}
                    body.update(router.admission.stats())
                    return self._json(200 if ready else 503, body)
                if self.path == "/replicas":
                    return self._json(200, {
                        "replicas": router.replica_views()})
                if self.path == "/metrics":
                    try:
                        text = _metrics.to_prometheus()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    data = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if self.path == "/debug/telemetry":
                    try:
                        snap = router.telemetry_snapshot()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, snap)
                if self.path == "/debug/timeseries":
                    try:
                        body = router.timeseries.describe()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                if self.path == "/debug/tenants":
                    # the fleet tenant view (ISSUE 16): the router's
                    # edge book + every routable replica's table +
                    # their Space-Saving merge
                    try:
                        body = router.tenant_debug()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                if self.path == "/debug/lifecycle":
                    # the fleet lifecycle view (ISSUE 17): per-spawn
                    # joined supervisor+replica phase records, the
                    # spawn-time rollup, and live replica records
                    try:
                        body = router.lifecycle_debug()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                return self._json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path not in ("/predict", "/generate"):
                    return self._json(404, {"error": "unknown path"})
                ctx = _rtrace.continue_from_headers(self.headers)
                if ctx.tenant_id is None:
                    # the router resolves the SAME billing fallback as
                    # the serving edge (fp:<fingerprint>, else anon) so
                    # a shed here and a decode on the replica land in
                    # one ledger row; _route_generate refines anon to a
                    # derived fingerprint before forwarding
                    fp = self.headers.get("X-Prefix-Fingerprint")
                    tid = _tledger.sanitize_tenant(f"fp:{fp}") \
                        if fp else None
                    ctx.tenant_id = tid or _tledger.ANON_TENANT
                # QoS class resolved ONCE at the edge (ISSUE 18): an
                # explicit valid X-Priority-Class wins, else the
                # tenant->class map, else the default tier.  The
                # resolved class rides the forwarded hop's headers so
                # router and replica agree on the tier.
                ctx.priority_class = _qos.resolve_class(
                    tenant_id=ctx.tenant_id,
                    explicit=ctx.priority_class)
                self._rt_ctx = ctx
                with _rtrace.activate(ctx):
                    if self.path == "/predict":
                        self._route_predict(ctx)
                    else:
                        self._route_generate(ctx)

            # --- /predict: buffered forward with transparent failover --
            def _route_predict(self, ctx):
                t_req = time.perf_counter()
                sp = _trace.begin("router.request", cat="router",
                                  endpoint="predict", **ctx.trace_args())
                status = "error"
                ticket = None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    deadline = router._deadline(ctx)
                    try:
                        ticket = router.admission.admit(
                            deadline=deadline,
                            priority_class=ctx.priority_class)
                    except ShedError as e:
                        status = "shed"
                        return self._json(
                            e.http_status,
                            {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After",
                                      _retry_after_header(e.retry_after))])
                    try:
                        code, hdrs, data, rid = router.forward_predict(
                            body, ctx,
                            content_type=self.headers.get(
                                "Content-Type",
                                "application/octet-stream"))
                    except ShedError as e:
                        status = "shed"
                        return self._json(
                            e.http_status,
                            {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After",
                                      _retry_after_header(e.retry_after))])
                    except Exception as e:
                        # a router bug must still answer the client
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    if sp is not None:
                        sp.args["replica"] = rid
                    status = ("ok" if code == 200 else
                              "client_error" if code == 400 else
                              "shed" if code in (429, 503) else "error")
                    self.send_response(code)
                    self.send_header(
                        "Content-Type",
                        hdrs.get("Content-Type",
                                 "application/octet-stream"))
                    self.send_header("Content-Length", str(len(data)))
                    self.send_header("X-Request-Id", ctx.request_id)
                    if "Retry-After" in hdrs:
                        self.send_header("Retry-After",
                                         hdrs["Retry-After"])
                    self.end_headers()
                    self.wfile.write(data)
                finally:
                    if ticket is not None:
                        ticket.release(ok=status == "ok")
                    router._finish_request("predict", status, sp, t_req,
                                           tenant_id=ctx.tenant_id,
                                           cls=ctx.priority_class)

            # --- /generate: streamed forward -------------------------
            def _route_generate(self, ctx):
                t_req = time.perf_counter()
                sp = _trace.begin("router.request", cat="router",
                                  endpoint="generate", **ctx.trace_args())
                status = "error"
                ticket = None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    try:
                        parsed = json.loads(body or b"{}")
                        prompt = [int(x) for x in
                                  parsed.get("input_ids", [])]
                    except Exception:
                        parsed = {}
                        prompt = []  # replica will 400 it; no prefix
                    # prefix-affinity fingerprint: the client's header
                    # wins; otherwise derive one from the parsed prompt
                    # so plain clients still get affinity.  Either way
                    # it is ONLY a routing hint — the engine matches
                    # real tokens, so a poisoned header cannot change
                    # the stream, only the replica it lands on.
                    fingerprint = self.headers.get(
                        "X-Prefix-Fingerprint")
                    if fingerprint is None and prompt:
                        from .serving import InferenceClient

                        fingerprint = InferenceClient.prefix_fingerprint(
                            prompt)
                        if ctx.tenant_id == _tledger.ANON_TENANT \
                                and fingerprint:
                            # refine the billing fallback with the
                            # derived fingerprint BEFORE forwarding, so
                            # router and replica book the same cohort
                            # key (the forwarded hop carries it)
                            ctx.tenant_id = _tledger.sanitize_tenant(
                                f"fp:{fingerprint}") \
                                or _tledger.ANON_TENANT
                    deadline = router._deadline(ctx)
                    try:
                        ticket = router.gen_admission.admit(
                            deadline=deadline,
                            priority_class=ctx.priority_class)
                    except ShedError as e:
                        status = "shed"
                        return self._json(
                            e.http_status,
                            {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After",
                                      _retry_after_header(e.retry_after))])
                    try:
                        status = router.forward_generate(
                            body, prompt, ctx, self,
                            fingerprint=fingerprint,
                            max_new_tokens=parsed.get(
                                "max_new_tokens"),
                            eos_token_id=parsed.get("eos_token_id"))
                    except Exception as e:
                        # best effort: before any stream bytes this is
                        # a clean 500; afterwards the socket just
                        # closes (the client's parser notices the
                        # missing final record)
                        status = "error"
                        try:
                            self._json(500, {"error":
                                             f"{type(e).__name__}: {e}"})
                        except Exception:  # pt-lint: ok[PT005]
                            pass  # headers already sent mid-stream
                finally:
                    if ticket is not None:
                        ticket.release(ok=status == "ok")
                    router._finish_request("generate", status, sp, t_req,
                                           tenant_id=ctx.tenant_id,
                                           cls=ctx.priority_class)

        self._httpd = _RouterHTTPServer((host, port), Handler)
        self._thread = None

    # ------------------------------------------------------------------
    # membership (the fleet drives these; also usable standalone)
    # ------------------------------------------------------------------
    def add_replica(self, rid, address):
        """Register a replica.  It starts `down` and enters rotation
        when the probe loop sees it ready (a just-launched replica must
        pass readiness before traffic, ISSUE 9 (c))."""
        breaker = CircuitBreaker(
            failure_threshold=self._breaker_threshold,
            reset_timeout=self._breaker_reset, clock=self.clock,
            name=f"router.{rid}")
        with self._lock:
            self._replicas[str(rid)] = _Replica(rid, address, breaker)
        self._note("router.replica_added", replica=str(rid),
                   address=str(address))
        self._publish_state_gauges()
        return self

    def update_replica(self, rid, address):
        """Point `rid` at a relaunched process (new address).  State
        resets to `down`; the probe loop re-admits it after readiness
        passes, counting a `router.readmissions`."""
        with self._lock:
            rep = self._replicas.get(str(rid))
        if rep is None:
            return self.add_replica(rid, address)
        with self._lock:
            rep.address = str(address)
            rep.state = "down"
            rep.signals = {}
            rep.missed_heartbeats = 0
            rep.probe_failures = 0
            rep.generation += 1
            rep.draining_requested = False
            rep.ever_beat = False  # the new process must beat before
            # heartbeat absence can count against it again
            rep.ever_forwarded = False  # the relaunch opened a fresh
            # spawn record: its first forward is a first again
            rep.breaker.record_success()  # fresh process, fresh slate
        self._note("router.replica_relaunched", replica=str(rid),
                   address=str(address))
        self._publish_state_gauges()
        return self

    def remove_replica(self, rid):
        with self._lock:
            self._replicas.pop(str(rid), None)
        self._publish_state_gauges()

    def mark_draining(self, rid):
        """Take `rid` out of rotation NOW — the fleet calls this BEFORE
        delivering SIGTERM, so by the time the replica's own
        `PreemptionGuard` flips it to draining no new traffic is headed
        there (no thundering 503s, ISSUE 9 (c))."""
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is None:
                return False
            rep.draining_requested = True
            if rep.state == "up":
                rep.state = "draining"
        self._note("router.replica_draining", replica=str(rid))
        self._publish_state_gauges()
        return True

    def note_replica_down(self, rid):
        """Immediate death notice (the fleet saw the process exit):
        faster than waiting out K missed heartbeats."""
        ejected = False
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is None:
                return False
            if rep.state not in ("down", "ejected"):
                ejected = rep.state != "draining"
                rep.state = "down"
        if ejected:
            _metrics.inc("router.ejections")
            self._note("router.replica_down", replica=str(rid))
        self._publish_state_gauges()
        return True

    def inflight_to(self, rid):
        """Router-side in-flight request count toward one replica (the
        fleet waits for this to hit 0 before SIGTERMing a drained
        replica)."""
        with self._lock:
            rep = self._replicas.get(str(rid))
            return sum(rep.inflight.values()) if rep is not None else 0

    def replica_views(self):
        with self._lock:
            return [r.view() for r in self._replicas.values()]

    def replica_summary(self):
        with self._lock:
            return {r.id: r.state for r in self._replicas.values()}

    def routable_count(self):
        with self._lock:
            return len(self._routable_locked())

    def readiness(self):
        if self.admission.draining:
            return False, "draining"
        if self.routable_count() == 0:
            return False, "no_replicas"
        return True, "ok"

    # ------------------------------------------------------------------
    # probe loop: readiness signals, heartbeats, state transitions
    # ------------------------------------------------------------------
    def probe_once(self):
        """One probe pass (the loop body; tests call it directly with a
        fake transport/heartbeat source).  Readiness probes every
        replica, folds in the heartbeat view, applies state
        transitions, republishes gauges, and re-tracks the edge
        admission capacities."""
        alive = None
        if self.heartbeats is not None:
            try:
                alive = {str(r) for r in self.heartbeats()}
            except Exception as e:  # pt-lint: ok[PT005]
                alive = None  # a broken heartbeat source must not
                # eject the whole fleet — fall back to probe-only
                # liveness for this pass (and leave a trace of it)
                self._note("router.heartbeat_source_error",
                           error=f"{type(e).__name__}: {e}")
        with self._lock:
            targets = [(r.id, r.address, r.generation)
                       for r in self._replicas.values()]
        for rid, address, gen in targets:
            ok, payload = self._probe_replica(address)
            self._apply_probe(rid, gen, ok, payload, alive)
        self._publish_state_gauges()
        self._retrack_capacity()

    def _probe_replica(self, address):
        try:
            code, _hdrs, body = self.transport.request(
                address, "GET", "/ready", timeout=max(
                    1.0, self.probe_interval * 4))
            try:
                payload = json.loads(body or b"{}")
            except ValueError:
                payload = {}
            payload["_ready"] = code == 200
            return True, payload
        except Exception:
            return False, None

    def _apply_probe(self, rid, gen, ok, payload, alive):
        readmitted = ejected = None
        came_up = False
        address = None
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.generation != gen:
                return  # relaunched mid-probe: stale result
            if ok:
                rep.probe_failures = 0
                rep.signals = payload
            else:
                rep.probe_failures += 1
            if alive is not None:
                if rid in alive:
                    rep.ever_beat = True
                    rep.missed_heartbeats = 0
                elif rep.ever_beat:
                    rep.missed_heartbeats += 1
                # never beat: this replica's heartbeat plane never came
                # up (fleet degrades it to probe-only liveness) — its
                # absence from `alive` is not evidence of death, and
                # counting it would brick a perfectly ready replica
            misses = max(rep.missed_heartbeats, rep.probe_failures)
            if rep.state in ("up", "draining"):
                if misses >= self.heartbeat_miss_k:
                    # deliberate drains exit quietly; anything else
                    # is an ejection (it held traffic until now)
                    ejected = not rep.draining_requested
                    rep.state = "ejected" if ejected else "down"
                elif ok and not payload.get("_ready") and \
                        str(payload.get("reason")) == "draining":
                    rep.state = "draining"
                elif rep.state == "draining" and ok \
                        and payload.get("_ready") \
                        and not rep.draining_requested:
                    # the replica's drain was observed, not requested
                    # by the fleet, and its readiness recovered: back
                    # into rotation (a fleet-requested drain sticks
                    # until SIGTERM/exit — flipping back would race
                    # the drain ordering)
                    rep.state = "up"
            elif rep.state in ("down", "ejected")  \
                    and ok and payload.get("_ready") and misses == 0:
                # first-ever admission is just startup; anything after
                # the replica has held traffic (or been relaunched) is
                # a re-admission worth counting
                if rep.ever_up:
                    readmitted = rep.state
                rep.state = "up"
                rep.ever_up = True
                rep.draining_requested = False
                rep.breaker.record_success()
                came_up = True
                address = rep.address
        if came_up and self.lifecycle is not None:
            # lifecycle (ISSUE 17): first probe-up closes the
            # spawn-to-routable interval (first-wins per spawn record —
            # a relaunch opened a fresh record, so its re-admission
            # stamps again), then the replica's own phase record is
            # fetched and attached DURABLY: a scale-down later must not
            # erase the spawn story the surge gate audits
            try:
                if self.lifecycle.stamp(rid, "first_probe_up"):
                    code, _hdrs, body = self.transport.request(
                        address, "GET", "/debug/lifecycle",
                        timeout=max(1.0, self.probe_interval * 4))
                    if code == 200:
                        self.lifecycle.attach_replica_record(
                            rid, json.loads(body or b"{}"))
            except Exception as e:  # pt-lint: ok[PT005]
                # observability of observability: a lost record is a
                # note, never a probe failure
                self._note("router.lifecycle_attach_failed",
                           replica=rid, error=type(e).__name__)
        if ejected:
            _metrics.inc("router.ejections")
            self._note("router.replica_ejected", replica=rid)
        elif ejected is False:
            self._note("router.replica_drained_out", replica=rid)
        if readmitted is not None:
            _metrics.inc("router.readmissions")
            self._note("router.replica_readmitted", replica=rid,
                       was=readmitted)

    def _probe_loop(self):
        while not self._probe_stop.wait(self.probe_interval):
            try:
                self.probe_once()
            except Exception as e:  # pt-lint: ok[PT005]
                # the probe loop is the router's heart — one bad pass
                # (a replica racing teardown, a malformed payload) must
                # not stop all future probing.  Leave evidence.
                self._note("router.probe_error",
                           error=f"{type(e).__name__}: {e}")

    def _retrack_capacity(self):
        """Edge admission capacity = what the routable fleet can
        actually run concurrently right now.  Published as
        `router.capacity{endpoint}` gauges (ISSUE 14) so the fleet's
        routable headroom is scrapeable next to the autoscaler's
        replica gauges — zero IS a meaningful reading (nothing
        routable), so the gauges publish unconditionally even though
        the controllers only re-track positive capacity."""
        predict_cap = 0
        gen_cap = 0
        with self._lock:
            for rid in self._routable_locked():
                sig = self._replicas[rid].signals
                predict_cap += int(sig.get("admission_limit")
                                   or sig.get("limit") or 1)
                eng = sig.get("engine") or {}
                gen_cap += int(eng.get("max_slots") or 0)
        _metrics.set_gauge("router.capacity", predict_cap,
                           endpoint="predict")
        _metrics.set_gauge("router.capacity", gen_cap,
                           endpoint="generate")
        if predict_cap > 0:
            self.admission.set_capacity(predict_cap)
        if gen_cap > 0:
            self.gen_admission.set_capacity(gen_cap)

    def _routable_locked(self):  # pt-lint: ok[PT102] (callers hold _lock)
        return [rid for rid, rep in self._replicas.items()
                if rep.state == "up"
                and rep.signals.get("_ready", False)
                and rep.breaker.state != "open"]

    def routable_ids(self):
        """Replica ids currently in rotation — the autoscaler's
        scale-down candidate set (a drain must target a replica that
        is actually carrying traffic state, never one already
        draining/ejected/down)."""
        with self._lock:
            return list(self._routable_locked())

    def affinity_counts(self):
        """Live prefix-affinity population per replica id: how many
        fingerprints in the bounded LRU map currently point at each
        replica.  The autoscaler uses this to pick the LEAST
        affinity-hot routable replica for scale-down — draining the
        replica most prefixes are warm on would trade every one of
        those tenants' TTFT for nothing."""
        with self._lock:
            counts: dict = {}
            for rid in self._affinity.values():
                counts[rid] = counts.get(rid, 0) + 1
            return counts

    # ------------------------------------------------------------------
    # pick + forward
    # ------------------------------------------------------------------
    def _pick(self, endpoint, exclude=(), fingerprint=None):
        """Least-loaded routable replica for `endpoint`, or None.
        Load = the replica's own admission view (stale by at most one
        probe) plus the router's live in-flight count toward it.

        With a `fingerprint` (ISSUE 13): prefer the replica this
        prefix last landed on — but ONLY while its load stays within
        `affinity_slack` of the least-loaded candidate (affinity must
        never become a hot spot), and only when it is currently
        routable (never a drained/ejected/breaker-open replica: those
        never enter the candidate set).  Every pick refreshes the
        bounded LRU fingerprint map, so the affinity self-corrects as
        the fleet changes."""
        loads = {}
        outcome = None
        with self._lock:
            for rid in self._routable_locked():
                if rid in exclude:
                    continue
                rep = self._replicas[rid]
                sig = rep.signals
                if endpoint == "generate":
                    eng = sig.get("engine") or {}
                    slots = max(1, int(eng.get("max_slots") or 1))
                    load = (float(eng.get("active_sequences") or 0)
                            + float(eng.get("waiting_sequences") or 0)
                            + rep.inflight["generate"]) / slots
                else:
                    limit = max(1, int(sig.get("admission_limit")
                                       or sig.get("limit") or 1))
                    load = (float(sig.get("inflight") or 0)
                            + float(sig.get("queued") or 0)
                            + rep.inflight["predict"]) / limit
                loads[rid] = load
            if not loads:
                return None
            pick = min(loads, key=lambda r: (loads[r], r))
            if fingerprint is not None:
                affine = self._affinity.get(fingerprint)
                if affine in loads and loads[affine] <= \
                        loads[pick] + self.affinity_slack:
                    pick = affine
                    outcome = "affine"
                else:
                    outcome = "least_loaded"
                self._affinity[fingerprint] = pick
                self._affinity.move_to_end(fingerprint)
                while len(self._affinity) > self.AFFINITY_CAP:
                    self._affinity.popitem(last=False)
        if outcome is not None:
            _metrics.inc("router.affinity", outcome=outcome)
        return pick

    def _begin_forward(self, rid, endpoint):
        first = False
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return None
            rep.inflight[endpoint] += 1
            if not rep.ever_forwarded:
                rep.ever_forwarded = True
                first = True
            address = rep.address
        if first and self.lifecycle is not None:
            # lifecycle (ISSUE 17): the spawn record's first routed
            # request (first-wins — the flag keeps the common path to
            # one boolean test, the ledger dedups relaunch races)
            try:
                self.lifecycle.stamp(rid, "first_routable_request")
            except Exception:  # pt-lint: ok[PT005]
                pass  # never fail a forward for a lost stamp
        return address

    def _end_forward(self, rid, endpoint):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.inflight[endpoint] = max(
                    0, rep.inflight[endpoint] - 1)

    def _forward_failed(self, rid, err):
        """Book a transport-level forward failure: feeds the breaker
        (pick skips open breakers) and leaves a flight event.  The
        probe loop does the actual ejection — one failed forward is a
        failover, not a funeral."""
        with self._lock:
            rep = self._replicas.get(rid)
            breaker = rep.breaker if rep is not None else None
        if breaker is not None:
            breaker.record_failure()
        self._note("router.forward_failed", replica=rid,
                   error=f"{type(err).__name__}: {err}")

    def _no_replica_shed(self, last_shed):
        """End of the failover loop with nothing served: prefer the
        honest replica-provided shed (its Retry-After reflects real
        queue depth); otherwise the fleet is gone — 503 no_replicas."""
        if last_shed is not None:
            code, hdrs, data = last_shed
            return code, hdrs, data
        _metrics.inc("resilience.shed_requests", reason="no_replicas")
        self._note("router.no_replicas")
        raise ShedError("no_replicas",
                        retry_after=self.probe_interval
                        * self.heartbeat_miss_k + 1.0,
                        detail="no routable replica")

    def forward_predict(self, body, ctx, content_type=None):
        """Forward one buffered /predict: returns (status, headers,
        body, replica_id).  Transparent failover on transport failure
        or replica shed, always under the SAME X-Request-Id (`ctx` is
        this hop's context; every attempt reuses its headers).  Raises
        ShedError("no_replicas") when nothing routable remains."""
        from ..resilience import faults as _faults

        hop = ctx.child()
        headers = {"Content-Type": content_type
                   or "application/octet-stream"}
        headers.update(hop.to_headers())
        tried: set = set()
        last_shed = None
        attempts = self.failover_retries + 1
        for attempt in range(attempts):
            rid = self._pick("predict", exclude=tried)
            if rid is None:
                break
            tried.add(rid)
            address = self._begin_forward(rid, "predict")
            if address is None:
                continue
            sp = _trace.begin("router.forward", cat="router",
                              replica=rid, endpoint="predict",
                              attempt=attempt, **ctx.trace_args())
            try:
                _faults.fire("router.forward", replica=rid,
                             endpoint="predict")
                self._breaker_allow(rid)
                code, hdrs, data = self.transport.request(
                    address, "POST", "/predict", body=body,
                    headers=headers, timeout=self.request_timeout)
            except CircuitOpenError:
                continue
            except Exception as e:
                self._forward_failed(rid, e)
                if attempt < attempts - 1:
                    _metrics.inc("router.failovers")
                continue
            finally:
                self._end_forward(rid, "predict")
                _trace.end(sp)
            self._breaker_success(rid)
            if code in (429, 503):
                # the replica is alive but shedding — its estimate was
                # fresher than our probe; try a less-loaded one, and
                # keep ITS Retry-After as the honest fallback answer
                self._maybe_mark_draining(rid, data)
                last_shed = (code, hdrs, data)
                continue
            return code, hdrs, data, rid
        code, hdrs, data = self._no_replica_shed(last_shed)
        return code, hdrs, data, None

    def _maybe_mark_draining(self, rid, data):
        try:
            if json.loads(data or b"{}").get("reason") == "draining":
                self.mark_draining(rid)
        except ValueError:  # pt-lint: ok[PT005]
            pass  # non-JSON shed body: the probe loop will notice

    def _breaker_allow(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
            breaker = rep.breaker if rep is not None else None
        if breaker is not None:
            breaker.allow()

    def _breaker_success(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
            breaker = rep.breaker if rep is not None else None
        if breaker is not None:
            breaker.record_success()

    def forward_generate(self, body, prompt_ids, ctx, handler,
                         fingerprint=None, max_new_tokens=None,
                         eos_token_id=None):
        """Proxy one /generate stream to the client behind `handler`.

        Failover contract (ISSUE 9 (b) + ISSUE 20): attempts rotate
        replicas under ONE request id while ZERO token lines have been
        written to the client.  Once tokens ARE delivered, a replica
        failure triggers a deterministic mid-stream RESUME: the router
        resubmits `prompt + delivered[:-1]` as the next leg's prompt
        (valid by the greedy determinism contract — delivered tokens
        are the argmax continuations) with the budget reduced
        accordingly, still under the same request id; the resume
        replica tail-prefills (usually a prefix-cache hit) and must
        reproduce `delivered[-1]` as its FIRST token — the divergence
        check.  The verify token is swallowed (the client already has
        it), so the stream continues from token N with zero replay and
        no client-visible seam beyond latency; the final record gains
        a `"resumed": n` field.  Resume is bounded
        (`stream_resume_max` legs), deadline-aware (never past the
        edge deadline) and class-gated (`stream_resume_classes`); any
        refusal, divergence, or replica exhaustion falls back LOUDLY
        to the single clean `interrupted` record carrying `output_ids`
        = prompt + delivered tokens — the stream NEVER replays or
        invents a token.  Returns the request's status label.
        `fingerprint` biases every pick toward the prefix-affine
        replica (see `_pick`); the header rides through untouched."""
        from ..resilience import faults as _faults

        hop = ctx.child()
        headers = {"Content-Type": "application/json"}
        headers.update(hop.to_headers())
        if fingerprint is not None:
            headers["X-Prefix-Fingerprint"] = str(fingerprint)
        prompt_ids = [int(x) for x in prompt_ids]
        max_new = max(1, int(max_new_tokens
                             if max_new_tokens is not None else 32))
        deadline_abs = self._deadline(ctx)
        tried: set = set()
        last_shed = None
        started = False          # client response headers sent?
        delivered: list = []     # token values already written out
        resumes = 0              # resume legs begun (ISSUE 20)
        verify_expect = None     # resume leg must reproduce this first
        pending_ok = False       # resume leg awaiting its first token
        last_token_at = None     # resume-gap clock anchor
        cur_body = body          # current leg's request body
        attempts = self.failover_retries + 1
        fresh_tries = 0
        while True:
            if not delivered and not started:
                if fresh_tries >= attempts:
                    break
                fresh_tries += 1
            rid = self._pick("generate", exclude=tried,
                             fingerprint=fingerprint)
            if rid is None:
                break
            tried.add(rid)
            address = self._begin_forward(rid, "generate")
            if address is None:
                continue
            resuming = bool(delivered or started)
            sp = _trace.begin("router.forward", cat="router",
                              replica=rid, endpoint="generate",
                              attempt=len(tried) - 1, resume=resumes,
                              **ctx.trace_args())
            stream = None
            try:
                _faults.fire("router.forward", replica=rid,
                             endpoint="generate")
                self._breaker_allow(rid)
                stream = self.transport.stream(
                    address, "/generate", cur_body, headers=headers,
                    timeout=self.request_timeout)
            except CircuitOpenError:
                self._end_forward(rid, "generate")
                _trace.end(sp)
                continue
            except Exception as e:
                self._forward_failed(rid, e)
                self._end_forward(rid, "generate")
                _trace.end(sp)
                if not resuming and fresh_tries < attempts:
                    _metrics.inc("router.failovers")
                continue
            try:
                self._breaker_success(rid)  # status line arrived
                if stream.status in (429, 503):
                    data = stream.read_body()
                    self._maybe_mark_draining(rid, data)
                    if not resuming:
                        last_shed = (stream.status,
                                     dict(stream.headers), data)
                    continue  # a shed resume leg: try the next replica
                if stream.status != 200:
                    if resuming:
                        # a deterministic 4xx/5xx on the ROUTER-built
                        # resume body is a fleet problem, not a client
                        # one: fall back to the interrupted record
                        raise ReplicaUnreachable(
                            f"{rid}: resume leg answered "
                            f"{stream.status}")
                    # deterministic replica answer (400 etc.): pass
                    # through — it would fail identically anywhere
                    data = stream.read_body()
                    handler._json(stream.status, _safe_json(data))
                    return ("client_error" if stream.status == 400
                            else "error")
                done_seen = False
                lines = stream.lines()
                while True:
                    # replica-read and client-write failures MUST be
                    # told apart (both raise OSError subclasses): a
                    # dead replica fails over / resumes / interrupts
                    # cleanly, a dead client cancels upstream — so the
                    # two I/O directions get separate try blocks
                    try:
                        line = next(lines)
                        _faults.fire("router.stream_read", replica=rid,
                                     delivered=len(delivered))
                    except StopIteration:
                        break
                    except (_faults.InjectedFault, OSError,
                            http.client.HTTPException) as e:
                        raise ReplicaUnreachable(
                            f"{rid}: {type(e).__name__}: {e}") from e
                    if not line.strip():
                        continue
                    evt = _safe_json(line)
                    has_token = "token" in evt
                    if verify_expect is not None and has_token:
                        # divergence check (ISSUE 20): the resume
                        # leg's first token re-derives delivered[-1];
                        # it is swallowed either way — the client
                        # already has it, and a mismatch must fall
                        # back to the clean interrupted record, never
                        # stream a wrong token
                        got = int(evt["token"])
                        injected = False
                        try:
                            _faults.fire("router.resume_verify",
                                         replica=rid, got=got)
                        except _faults.InjectedFault:
                            injected = True
                        if injected or got != verify_expect:
                            _metrics.inc("router.stream_resumes",
                                         outcome="diverged")
                            self._note("router.resume_diverged",
                                       replica=rid,
                                       expected=int(verify_expect),
                                       got=got, injected=injected,
                                       delivered=len(delivered))
                            return self._interrupt_stream(
                                handler, ctx, rid, prompt_ids,
                                delivered,
                                "resume diverged from delivered "
                                "prefix")
                        verify_expect = None
                        self._resume_established(
                            rid, last_token_at, len(delivered))
                        last_token_at = self.clock()
                        continue   # swallowed: the client has it
                    if pending_ok and has_token:
                        # resume leg with nothing to verify (the break
                        # landed between headers and the first token):
                        # established at its first real token
                        pending_ok = False
                        self._resume_established(
                            rid, last_token_at, len(delivered))
                    if evt.get("done") and resumes:
                        # the client learns its stream absorbed
                        # failovers (loadgen counts resumed_streams)
                        evt["resumed"] = resumes
                        line = json.dumps(evt).encode() + b"\n"
                    try:
                        if not started:
                            started = True
                            handler.send_response(200)
                            handler.send_header(
                                "Content-Type", "application/x-ndjson")
                            handler.send_header("X-Request-Id",
                                                ctx.request_id)
                            handler.send_header("Connection", "close")
                            handler.end_headers()
                        handler.wfile.write(line)
                        handler.wfile.flush()
                    except (BrokenPipeError, ConnectionError,
                            OSError) as e:
                        # the CLIENT went away: closing the replica
                        # stream (finally below) cancels the sequence
                        self._note("router.client_disconnect",
                                   replica=rid,
                                   error=f"{type(e).__name__}: {e}")
                        return "client_error"
                    if has_token:
                        delivered.append(int(evt["token"]))
                        last_token_at = self.clock()
                    if evt.get("done"):
                        done_seen = True
                        break
                if done_seen:
                    return "ok"
                # replica stream ended without a final record: the
                # process died mid-generation (kill -9 chaos path)
                raise ReplicaUnreachable(
                    f"{rid}: stream ended without final record")
            except (ReplicaUnreachable, OSError,
                    http.client.HTTPException) as e:
                self._forward_failed(rid, e)
                if not delivered and not started:
                    if fresh_tries < attempts:
                        _metrics.inc("router.failovers")
                    continue  # zero tokens delivered: safe to fail over
                # tokens already delivered: deterministic mid-stream
                # resume (ISSUE 20), bounded / deadline- / class-gated
                refusal = self._resume_refusal(ctx, resumes,
                                               deadline_abs)
                if refusal is not None:
                    _metrics.inc("router.stream_resumes",
                                 outcome="exhausted")
                    self._note("router.resume_refused", replica=rid,
                               reason=refusal,
                               delivered=len(delivered))
                    return self._interrupt_stream(
                        handler, ctx, rid, prompt_ids, delivered,
                        f"replica failed mid-stream: "
                        f"{type(e).__name__}")
                resumes += 1
                cur_body, verify_expect = self._resume_body(
                    prompt_ids, delivered, max_new, eos_token_id,
                    resumes)
                pending_ok = verify_expect is None
                if last_token_at is None:
                    last_token_at = self.clock()
                self._note("router.stream_resume", replica=rid,
                           leg=resumes, delivered=len(delivered),
                           error=f"{type(e).__name__}: {e}")
                continue
            finally:
                self._end_forward(rid, "generate")
                _trace.end(sp)
                if stream is not None:
                    stream.close()
        if started or delivered:
            # mid-stream loss with no replica left to resume on
            _metrics.inc("router.stream_resumes", outcome="exhausted")
            self._note("router.resume_refused", reason="no_replica",
                       delivered=len(delivered))
            return self._interrupt_stream(
                handler, ctx, None, prompt_ids, delivered,
                "replica failed mid-stream: no replica available "
                "for resume")
        # nothing started: we can still answer with a clean status
        try:
            code, hdrs, data = self._no_replica_shed(last_shed)
        except ShedError as e:
            handler._json(e.http_status,
                          {"error": str(e), "reason": e.reason},
                          headers=[("Retry-After",
                                    _retry_after_header(e.retry_after))])
            return "shed"
        handler._json(code, _safe_json(data),
                      headers=[("Retry-After", hdrs["Retry-After"])]
                      if "Retry-After" in hdrs else ())
        return "shed"

    # --- mid-stream resume internals (ISSUE 20) -----------------------
    def _resume_refusal(self, ctx, resumes, deadline_abs):
        """Why a mid-stream resume must NOT be attempted, or None when
        it may: budget spent, class not served, or the edge deadline
        already passed (resuming a stream nobody will wait for only
        burns a tail-prefill)."""
        if resumes >= self.stream_resume_max:
            return "budget"
        cls = ctx.priority_class or _qos.DEFAULT_CLASS
        if cls not in self.stream_resume_classes:
            return "class"
        if deadline_abs is not None and self.clock() >= deadline_abs:
            return "deadline"
        return None

    @staticmethod
    def _resume_body(prompt_ids, delivered, max_new, eos_token_id,
                     leg):
        """The resume leg's request body + the verify token.

        `prompt + delivered[:-1]` is resubmitted as the prompt — by
        the greedy determinism contract its argmax continuation is
        exactly `delivered[-1]`, which the resume replica re-derives
        as its first token (the divergence check; billed nowhere,
        `prebilled_tokens=1`).  The budget grows by that one verify
        token so the stream still ends at the original `max_new` —
        including the edge where every budgeted token was already
        delivered and only the final record was lost (a one-token
        leg that finishes `length`/`eos` immediately)."""
        if delivered:
            ids = list(prompt_ids) + [int(t) for t in delivered[:-1]]
            budget = max_new - len(delivered) + 1
            verify = int(delivered[-1])
        else:
            # broke between the response headers and the first token:
            # a plain full-budget resubmit, nothing to verify
            ids = list(prompt_ids)
            budget = max_new
            verify = None
        body = {"input_ids": ids,
                "max_new_tokens": max(1, int(budget)),
                "resume": int(leg),
                "prebilled_tokens": 0 if verify is None else 1}
        if eos_token_id is not None:
            body["eos_token_id"] = int(eos_token_id)
        return json.dumps(body).encode(), verify

    def _resume_established(self, rid, last_token_at, n_delivered):
        """A resume leg reconnected the stream: count it and attribute
        the client-visible gap (last delivered token -> the resumed
        leg's verify/first token)."""
        _metrics.inc("router.stream_resumes", outcome="ok")
        gap_ms = None
        if last_token_at is not None:
            gap_ms = max(0.0, (self.clock() - last_token_at) * 1e3)
            _metrics.observe("router.resume_gap_ms", gap_ms)
        self._note("router.stream_resumed", replica=rid,
                   delivered=n_delivered,
                   gap_ms=None if gap_ms is None
                   else round(gap_ms, 3))

    def _interrupt_stream(self, handler, ctx, rid, prompt_ids,
                          delivered, why):
        """The LOUD fallback: one clean `interrupted` record carrying
        the resumable prefix — never a replayed or invented token."""
        final = {
            "interrupted": True,
            "error": why,
            "finish_reason": "replica_lost",
            "request_id": ctx.request_id,
            "tokens_delivered": len(delivered),
            "output_ids": list(prompt_ids) + [int(t)
                                              for t in delivered],
        }
        try:
            handler.wfile.write(json.dumps(final).encode() + b"\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):  # pt-lint: ok[PT005]
            pass  # client gone too: nothing left to tell it
        self._note("router.stream_interrupted", replica=rid,
                   delivered=len(delivered))
        return "interrupted"

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _deadline(self, ctx=None):
        """Edge deadline: the router's request timeout, tightened by a
        client-declared X-Deadline-Ms budget (ISSUE 18) — a request
        that cannot finish inside its own budget should shed with
        `deadline`, not camp the queue."""
        deadline = (None if self.request_timeout is None
                    else self.clock() + self.request_timeout)
        if ctx is not None and ctx.deadline_ms is not None:
            client_dl = self.clock() + ctx.deadline_ms / 1e3
            deadline = (client_dl if deadline is None
                        else min(deadline, client_dl))
        return deadline

    def _finish_request(self, endpoint, status, sp, t_req,
                        tenant_id=None, cls=None):
        dt_ms = (time.perf_counter() - t_req) * 1e3
        if sp is not None:
            sp.args["status"] = status
        _trace.end(sp)
        _metrics.observe("router.request_ms", dt_ms,
                         endpoint=endpoint, status=status)
        _metrics.inc("router.requests", endpoint=endpoint,
                     status=status)
        if self.tenant_ledger is not None:
            # edge billing (ISSUE 16): sheds and failovers the fleet
            # never served still bill the right tenant (`interrupted`
            # books as error — the bounded-status discipline)
            self.tenant_ledger.record_request(tenant_id, status)
        # fleet-level SLO ledger (ISSUE 14): every edge shed and every
        # request the failover machinery could NOT save burns budget —
        # the burn rate over this ledger is what the autoscaler scales
        # on.  Client-fault 400s are excluded (same rule as serving:
        # the availability promise is about the fleet, and a
        # misbehaving client must not buy itself more replicas).
        if status == "ok":
            self.slo.observe(endpoint, dt_ms, ok=True, cls=cls)
        elif status == "shed":
            self.slo.record_shed(endpoint, "edge", cls=cls)
        elif status in ("error", "interrupted", "timeout"):
            self.slo.observe(endpoint, dt_ms, ok=False, reason=status,
                             cls=cls)

    def _publish_state_gauges(self):
        counts = dict.fromkeys(_REPLICA_STATES, 0)
        with self._lock:
            for rep in self._replicas.values():
                counts[rep.state] = counts.get(rep.state, 0) + 1
        for state, n in counts.items():
            _metrics.set_gauge("router.replicas", n, state=state)

    @staticmethod
    def _note(kind, **data):
        try:
            from ..observability import flight as _flight

            _flight.record(kind, **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: routing must
            # route even when telemetry is broken)

    def telemetry_snapshot(self):
        import os as _os

        ready, reason = self.readiness()
        # SLO report first: it publishes the slo.* gauges the metrics
        # snapshot should carry (same ordering as serving's snapshot)
        slo_report = self.slo.report()
        snap = {
            "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": _os.getpid(),
            "role": "router",
            "metrics": _metrics.snapshot(),
            "slo": slo_report,
            "admission": self.admission.stats(),
            "gen_admission": self.gen_admission.stats(),
            "readiness": {"ready": ready, "reason": reason},
            "replicas": self.replica_views(),
            "timeseries": self.timeseries.stats(),
        }
        if self.tenant_ledger is not None:
            snap["tenants"] = self.tenant_ledger.snapshot()
        if self.lifecycle is not None:
            # the fleet's spawn records + rollup (ISSUE 17) — joined
            # supervisor/replica views, no live re-fetch (the full
            # fleet view with live replica records is /debug/lifecycle)
            snap["lifecycle"] = self.lifecycle.fleet_view()
        return snap

    def tenant_debug(self):
        """GET /debug/tenants body: the live-fleet tenant view.

        `router` is this edge's own book (every answered request,
        including sheds no replica saw); `replicas` holds each routable
        replica's ledger snapshot fetched over HTTP; `fleet` is their
        Space-Saving merge — REPLICA books only, because router and
        replica both bill requests at their own hop and summing the two
        would double-count (`tools/telemetry_agg.py` applies the same
        rule to exporter dumps).  An unreachable replica is skipped and
        named in `unreachable` — a partial fleet view says so."""
        with self._lock:
            targets = [(rep.id, rep.address)
                       for rep in self._replicas.values()
                       if rep.state in ("up", "draining")]
        per, unreachable = {}, []
        for rid, address in sorted(targets):
            try:
                code, _hdrs, body = self.transport.request(
                    address, "GET", "/debug/tenants",
                    timeout=max(1.0, self.probe_interval * 4))
                snap = json.loads(body or b"{}")
                if code == 200 and isinstance(snap, dict):
                    per[rid] = snap
                else:
                    unreachable.append(rid)
            except Exception:
                unreachable.append(rid)
        out = {"role": "router", "replicas": per,
               "fleet": _tledger.merge_snapshots(list(per.values()))}
        if self.tenant_ledger is not None:
            out["router"] = self.tenant_ledger.snapshot()
        if unreachable:
            out["unreachable"] = unreachable
        return out

    def lifecycle_debug(self):
        """GET /debug/lifecycle body: the fleet lifecycle view.

        `fleet` is the supervisor's joined per-spawn records +
        percentile rollup (durable — scale-downs keep their story);
        `replicas` holds each routable replica's LIVE ledger record
        fetched over HTTP (a replica that has served shows first_token
        here before the durable record learns it).  An unreachable
        replica is skipped and named in `unreachable`."""
        with self._lock:
            targets = [(rep.id, rep.address)
                       for rep in self._replicas.values()
                       if rep.state in ("up", "draining")]
        per, unreachable = {}, []
        for rid, address in sorted(targets):
            try:
                code, _hdrs, body = self.transport.request(
                    address, "GET", "/debug/lifecycle",
                    timeout=max(1.0, self.probe_interval * 4))
                snap = json.loads(body or b"{}")
                if code == 200 and isinstance(snap, dict):
                    per[rid] = snap
                else:
                    unreachable.append(rid)
            except Exception:
                unreachable.append(rid)
        out = {"role": "router", "replicas": per}
        if self.lifecycle is not None:
            out["fleet"] = self.lifecycle.fleet_view()
        if unreachable:
            out["unreachable"] = unreachable
        return out

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def address(self):
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self, probe=True):
        self._serving = True
        self.timeseries.start()
        if probe:
            # one synchronous pass so capacities and readiness reflect
            # the fleet BEFORE the first request can race the loop
            self.probe_once()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, daemon=True,
                name="paddle-tpu-router-probe")
            self._probe_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="paddle-tpu-router")
        self._thread.start()
        return self

    def shutdown(self, drain_timeout=None):
        with self._shutdown_lock:
            first = not self._shutdown_done
            self._shutdown_done = True
        if not first:
            return True
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2)
        self.timeseries.stop()
        drained = self.admission.drain(timeout=drain_timeout)
        drained = self.gen_admission.drain(timeout=drain_timeout) \
            and drained
        if self._serving:
            self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd.server_close()
        return drained


class _RouterHTTPServer(ThreadingHTTPServer):
    """Same rationale as serving._ServingHTTPServer: the stdlib backlog
    of 5 sheds with raw TCP RSTs under bursts; shedding is the edge
    AdmissionController's decision."""

    request_queue_size = 128
    daemon_threads = True


def _safe_json(data):
    try:
        obj = json.loads(data if isinstance(data, (str, bytes))
                         else b"{}")
        return obj if isinstance(obj, dict) else {"body": obj}
    except ValueError:
        return {"body": repr(data[:200] if data else b"")}
