"""paddle_tpu.inference: deployment predictor API.

Role parity: `paddle.inference.Config` / `create_predictor` /
`AnalysisPredictor` (`paddle/fluid/inference/api/analysis_predictor.h:100`,
SURVEY §2.4). The reference runs an IR pass pipeline (fusion, memory reuse,
TensorRT capture) before an interpreter; on TPU the saved artifact is
already an AOT-compiled XLA program (`jax.export` serialization produced by
`paddle_tpu.static.save_inference_model` or `jit.save`), so the predictor's
job reduces to input/output handle marshalling around `Exported.call` —
zero-copy in the same sense (device buffers in, device buffers out).
"""
from __future__ import annotations

import os

import numpy as np


class Config:
    """Predictor configuration (paths + toggles; graph-opt toggles are
    accepted no-ops — XLA owns those decisions)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and params_file is None:
            # single path prefix form
            self.path_prefix = prog_file
        else:
            self.path_prefix = None
            if prog_file is not None:
                self.path_prefix = os.path.splitext(prog_file)[0]
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_device = "tpu"
        self.mem_optim = True
        self.ir_optim = True

    def set_model(self, prog_file, params_file=None):
        self.path_prefix = os.path.splitext(prog_file)[0]
        self.prog_file = prog_file
        self.params_file = params_file

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_device = "gpu"

    def disable_gpu(self):
        self._use_device = "cpu"

    def enable_xpu(self, *a, **kw):
        self._use_device = "xpu"

    def switch_ir_optim(self, x=True):
        self.ir_optim = x

    def enable_memory_optim(self, x=True):
        self.mem_optim = x

    def set_cpu_math_library_num_threads(self, n):
        pass

    def disable_glog_info(self):
        pass

    def enable_tensorrt_engine(self, *a, **kw):
        pass  # no TensorRT on TPU; XLA is the engine

    def summary(self):
        return f"Config(path={self.path_prefix}, device={self._use_device})"


class PredictorTensor:
    """Input/output handle (parity: paddle.inference zero-copy Tensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    def __init__(self, config):
        from ..static.io import load_inference_model

        self.config = config
        prog, feed_names, fetch_names = load_inference_model(
            config.path_prefix)
        self._prog = prog
        self._inputs = {n: PredictorTensor(n) for n in feed_names}
        self._outputs = {n: PredictorTensor(n) for n in fetch_names}

    def get_input_names(self):
        return list(self._inputs)

    def get_output_names(self):
        return list(self._outputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Run: either positional list of np arrays, or pre-filled handles."""
        if inputs is not None:
            for n, v in zip(self._prog.feed_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(v))
        feed = {n: h._value for n, h in self._inputs.items()}
        outs = self._prog._run(feed, return_numpy=True)
        for n, v in zip(self._prog.fetch_names, outs):
            self._outputs[n]._value = v
        if inputs is not None:
            return outs
        return True


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(*a, **kw):
    raise NotImplementedError(
        "mixed-precision conversion happens at save time on TPU: export "
        "under amp.auto_cast instead")



class DataType:
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


Tensor = PredictorTensor  # reference paddle.inference.Tensor


def get_version():
    from .. import __version__

    return f"paddle_tpu {__version__} (PJRT/XLA inference)"


def _get_phi_kernel_name(op_name):
    return op_name  # one op layer here; names are already kernel names


def get_num_bytes_of_data_type(dtype):
    import numpy as np

    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT tier (README Scope notes)


def get_trt_runtime_version():
    return (0, 0, 0)


class XpuConfig:
    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "XPU is a second-vendor backend subsumed by PJRT "
            "(README Scope notes)")


class PredictorPool:
    """Pool of predictors over one config (reference PredictorPool):
    size-many independently steppable predictors."""

    def __init__(self, config, size=1):
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrive(self, idx):
        return self._predictors[idx]

    retrieve = retrive  # reference spells it 'retrive'
