"""Python side of the C inference ABI (paddle_tpu/native/src/capi.cc).

Role parity: `paddle/fluid/inference/capi_exp/` (C API) — the reference
exposes its predictor to C/Go through a C ABI; ours exposes the AOT XLA
predictor the same way. The C library talks to this module exclusively
through (bytes, shape, dtype-code) triples so it never needs the NumPy C
API: `capi.cc` packs raw buffers into PyBytes and unpacks the returned
triples back into malloc'd C buffers.

Handles are process-local integer ids (the C side is free-threaded; the
registry is guarded by the GIL which the C side holds on every call).
"""
from __future__ import annotations

import numpy as np

# codes shared with paddle_tpu.inference.DataType and capi.cc
_DTYPES = {
    0: np.float32,
    1: np.int64,
    2: np.int32,
    3: np.uint8,
    4: np.int8,
    5: np.float16,
    7: np.bool_,
}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}
_BF16_CODE = 6

_registry: dict = {}
_next_id = 1


def create(path_prefix: str) -> int:
    """Load an exported inference model; returns a handle (>0)."""
    global _next_id
    from . import Config, Predictor

    pred = Predictor(Config(path_prefix))
    h = _next_id
    _next_id += 1
    _registry[h] = pred
    return h


def input_num(h: int) -> int:
    return len(_registry[h].get_input_names())


def output_num(h: int) -> int:
    return len(_registry[h].get_output_names())


def io_name(h: int, is_input: int, idx: int) -> str:
    pred = _registry[h]
    names = pred.get_input_names() if is_input else pred.get_output_names()
    return names[idx]


def _decode(triple):
    data, shape, code = triple
    if code == _BF16_CODE:
        import jax.numpy as jnp

        arr = np.frombuffer(data, dtype=jnp.bfloat16)
    elif code in _DTYPES:
        arr = np.frombuffer(data, dtype=_DTYPES[code])
    else:
        raise ValueError(f"capi: unknown dtype code {code}")
    return arr.reshape(shape)


def _encode(arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype.name == "bfloat16":
        code = _BF16_CODE
    else:
        code = _CODES.get(arr.dtype)
        if code is None:  # e.g. float64 from a CPU-run program: narrow
            arr = arr.astype(np.float32)
            code = 0
    return arr.tobytes(), tuple(int(s) for s in arr.shape), code


def run(h: int, inputs):
    """inputs: list of (bytes, shape-tuple, dtype-code). Returns the same
    triple format for every fetch output."""
    pred = _registry[h]
    arrs = [_decode(t) for t in inputs]
    outs = pred.run(arrs)
    return [_encode(np.asarray(o)) for o in outs]


def destroy(h: int) -> int:
    return 1 if _registry.pop(h, None) is not None else 0
