"""HTTP inference server over the AOT predictor.

Role parity: the reference's deployment tier around `AnalysisPredictor`
(`paddle/fluid/inference/api/` + the C/Go serving surfaces and Paddle
Serving). TPU-first: the model is a saved `jit.save` export (compiled
once at load); the server is a thin host loop — request decode, one
compiled call, response encode — because XLA owns all scheduling.

Protocol (stdlib-only, zero heavy deps):
  POST /predict   body = .npz archive (numpy savez) with one array per
                  model input, keyed by feed name (or arr_0.. in feed
                  order); response = .npz with one array per fetch name.
  GET  /health    liveness: {"status": "ok", "inputs", "outputs"} while
                  the process is up (including during drain).
  GET  /ready     readiness: 200 while accepting traffic; 503 with a
                  reason while draining or while the last `ready_window`
                  predictor calls ALL failed (load balancers route on
                  this; liveness keeps the process from being killed
                  mid-drain).
  GET  /metrics   Prometheus text exposition: every registry counter/
                  gauge/histogram (cumulative `_bucket{le=...}` series
                  included) plus the `slo.*` gauges — the scrape plane
                  (docs/OBSERVABILITY.md).
  GET  /debug/telemetry   JSON snapshot: metrics, the SLO report
                  (windowed burn rate, shed reasons), admission stats,
                  readiness, recent flight events.
  GET  /debug/tenants     per-tenant metering (ISSUE 16): the bounded
                  top-K tenant table + `~other` overflow bucket from the
                  `TenantLedger` — requests by status, prefill tokens
                  computed/saved, decode tokens, decode-slot-ms, KV
                  page-seconds, TTFT/ITL summaries.  This JSON surface
                  is DELIBERATELY not rendered on /metrics (cardinality
                  discipline — docs/OBSERVABILITY.md).
  GET  /debug/lifecycle   this process's spawn-phase record (ISSUE 17):
                  proc_spawn → imports → weight_load → warmup →
                  announce (→ first_token) with per-phase ms and the
                  per-program compile sub-ledger.

Tenant identity (ISSUE 16): `X-Tenant-Id` names who to BILL.  Parsed at
the edge next to `X-Request-Id`; a request without one falls back to
`fp:<prefix-fingerprint>` (the X-Prefix-Fingerprint routing hint — the
natural cohort key for a shared-prefix population) and finally to
`anon`, so EVERY request lands in exactly one ledger row.

Request identity (observability/request_trace.py): every /predict
response echoes `X-Request-Id`; incoming `X-Request-Id`/`traceparent`
headers are continued (same id, next hop), bare requests get a minted
id.  Phases — queue wait, admission, predict, serialize — land as
spans on the span tracer (args carry the request id) and as
`serving.phase_ms{phase=...}` histogram observations; the final status
feeds `serving.requests{status}` / `serving.request_ms{status}` and
the per-endpoint `SLOTracker` (sheds with their reason labels).

Status mapping (docs/RESILIENCE.md): deterministic request errors
(wrong dtype/rank/key, undecodable body) → 400; admission sheds and
deadline overruns → 429/503 + `Retry-After`; everything else → 500.

Overload behavior: every request passes the `AdmissionController`
(bounded queue + concurrency limit + deadline-aware shedding, env knobs
`PADDLE_TPU_MAX_INFLIGHT` / `PADDLE_TPU_QUEUE_DEPTH`) BEFORE touching
the predictor lock, so saturation sheds cheap 429s instead of stacking
timeouts.  `shutdown()` is a graceful drain: stop admitting → finish
in-flight (up to `PADDLE_TPU_DRAIN_TIMEOUT`) → close the socket.

Client helper: `InferenceClient` wraps the same protocol with a
configurable timeout and bounded retry on 429/503 honoring Retry-After.
"""
from __future__ import annotations

import io
import json
import math
import os
import queue
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from . import Config, create_predictor
from . import qos as _qos
from ..observability import lifecycle as _lifecycle
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tenant_ledger as _tledger
from ..observability import timeseries as _ts
from ..observability import trace as _trace
from ..observability.slo import SLOTracker
from ..resilience.overload import _env_num

__all__ = ["InferenceServer", "InferenceClient", "StreamInterrupted",
           "serve"]

# error classes that cannot be transient: no retry, no batch bisection
_DETERMINISTIC_ERRORS = (TypeError, ValueError, KeyError, IndexError,
                         AttributeError)

_ARR_KEY = re.compile(r"arr_(\d+)$")


# the serving replica's declared timeseries set (ISSUE 15): the queue /
# batch / token signals whose rates and derivatives answer "how fast is
# pressure growing" — served on GET /debug/timeseries and shipped
# incrementally in exporter dumps.  Bare names sum their label variants.
SERVING_SERIES = (
    "serving.inflight", "serving.queue_depth", "serving.admission_limit",
    "serving.requests", "resilience.shed_requests",
    "engine.active_sequences", "engine.waiting_sequences",
    "engine.batch_occupancy", "engine.page_utilization", "engine.tokens",
)


class _ServingHTTPServer(ThreadingHTTPServer):
    """stdlib default listen backlog is 5: under a connection burst the
    OS sheds with raw TCP RSTs before admission control ever sees the
    request.  Shedding is the AdmissionController's decision (a polite
    429 + Retry-After), so the accept backlog must comfortably exceed
    the admission queue."""

    request_queue_size = 128


def _positional_order(keys):
    """np.savez default keys sorted by NUMERIC suffix: plain
    `sorted()` puts arr_10 before arr_2, silently permuting the feeds
    of any model with more than 10 inputs.  Non-arr_N keys sort after,
    lexicographically (mixed keysets stay deterministic)."""
    def rank(k):
        m = _ARR_KEY.fullmatch(k)
        return (0, int(m.group(1)), k) if m else (1, 0, k)

    return sorted(keys, key=rank)


def _retry_after_header(seconds):
    """HTTP Retry-After is a non-negative INTEGER of seconds."""
    return str(max(0, int(math.ceil(float(seconds)))))


class InferenceServer:
    """Serve one predictor. `start()` returns immediately (daemon thread);
    `serve_forever()` blocks. Concurrent requests serialize around the
    predictor (one device queue) via a lock, behind admission control.

    Resilience (docs/RESILIENCE.md): each request runs under a retry
    policy (`request_retries` attempts within the `request_timeout`
    deadline); when retries are exhausted and every input shares a
    splittable leading batch dim, the request DEGRADES — the batch is
    halved recursively (down to single items), halves run independently
    and results re-concatenate, so one poisoned/oversized example costs
    its half-batch a recompile instead of failing the whole request.

    Overload/preemption: `admission` (an
    `resilience.overload.AdmissionController`) gates every request;
    `shutdown()` drains gracefully and is idempotent; pass a
    `resilience.preemption.PreemptionGuard` to `install_preemption()`
    (or let `serve()` do it) and SIGTERM turns into drain-then-exit.
    """

    def __init__(self, model_path=None, host: str = "127.0.0.1",
                 port: int = 0, request_retries: int = 2,
                 request_timeout: float = 30.0, max_inflight=None,
                 queue_depth=None, drain_timeout=None, ready_window=8,
                 predictor=None, engine=None):
        from ..resilience.overload import AdmissionController, ShedError
        from ..resilience.retry import RetryPolicy

        if predictor is not None:
            self._predictor = predictor
        elif model_path is not None:
            self._predictor = create_predictor(Config(model_path))
        elif engine is None:
            raise ValueError("InferenceServer needs a model_path, a "
                             "predictor, or an engine")
        else:
            self._predictor = None  # generate-only deployment
        # continuous-batching engine behind POST /generate (ISSUE 8):
        # its OWN AdmissionController, sized to the engine's true
        # capacity (batch slots concurrently decoding, a queue on top)
        # — shedding starts only past actual saturation, not at the
        # predictor lock's conservative default
        self.engine = engine
        # per-tenant metering (ISSUE 16): adopt the engine's ledger so
        # serving-edge request billing and engine-side token billing
        # share ONE book (conservation is per-book); predict-only
        # deployments get their own.  None when the plane is off —
        # every call site guards, so detached telemetry pays nothing.
        self.tenant_ledger = getattr(engine, "tenant_ledger", None)
        if self.tenant_ledger is None and _tledger.enabled() \
                and _metrics.enabled():
            self.tenant_ledger = _tledger.TenantLedger()
        self.gen_admission = None
        if engine is not None:
            self.gen_admission = AdmissionController(
                max_inflight=engine.config.max_slots,
                queue_depth=queue_depth, name="generate")
        self._plock = threading.Lock()
        self._request_timeout = (None if request_timeout is None
                                 else float(request_timeout))
        self._retry = RetryPolicy(
            "serving", max_attempts=max(1, int(request_retries)),
            base_delay=0.01, max_delay=0.25, deadline=request_timeout,
            # deterministic request errors (wrong dtype/rank for the
            # model) fail identically on every retry AND every split —
            # surface them immediately (no retry, and _run_resilient
            # re-raises them without bisecting the batch)
            give_up_on=_DETERMINISTIC_ERRORS)
        self.admission = AdmissionController(
            max_inflight=max_inflight, queue_depth=queue_depth,
            name="serving")
        # SLO ledger behind /debug/telemetry and the slo.* gauges on
        # /metrics: env knobs so a deployment declares its promise
        # without code (defaults: 1 s latency target, 99.9% availability
        # over a 5-minute window)
        self.slo = SLOTracker(
            window_s=_env_num("PADDLE_TPU_SLO_WINDOW", 300.0, float))
        self.slo.objective(
            "predict",
            latency_target_ms=_env_num("PADDLE_TPU_SLO_LATENCY_MS",
                                       1000.0, float),
            availability=_env_num("PADDLE_TPU_SLO_AVAILABILITY", 0.999,
                                  float))
        if engine is not None:
            # generation is a long-poll stream: the latency objective
            # covers time-to-completion, so default it far laxer than
            # one-shot predict
            self.slo.objective(
                "generate",
                latency_target_ms=_env_num(
                    "PADDLE_TPU_SLO_GENERATE_LATENCY_MS", 30000.0, float),
                availability=_env_num("PADDLE_TPU_SLO_AVAILABILITY",
                                      0.999, float))
            # time-to-first-token is its own SLO phase (ISSUE 13): at a
            # shared-prefix workload TTFT — not completion time — is
            # what the prefix cache buys, so it gets its own target and
            # burn accounting next to the stream-completion objective
            self.slo.objective(
                "ttft",
                latency_target_ms=_env_num(
                    "PADDLE_TPU_SLO_TTFT_MS", 5000.0, float),
                availability=_env_num("PADDLE_TPU_SLO_AVAILABILITY",
                                      0.999, float))
        # per-class objectives (ISSUE 18): the PAID class carries its
        # own explicit promise (env-tunable; defaults mirror the
        # endpoint objective) so its burn is tracked against what IT
        # was sold, not the blended fleet average; free/batch inherit
        # the endpoint objective in per-class burn computation
        paid_avail = _env_num("PADDLE_TPU_SLO_PAID_AVAILABILITY",
                              _env_num("PADDLE_TPU_SLO_AVAILABILITY",
                                       0.999, float), float)
        self.slo.objective(
            "predict", cls="paid",
            latency_target_ms=_env_num("PADDLE_TPU_SLO_LATENCY_MS",
                                       1000.0, float),
            availability=paid_avail)
        if engine is not None:
            self.slo.objective(
                "generate", cls="paid",
                latency_target_ms=_env_num(
                    "PADDLE_TPU_SLO_GENERATE_LATENCY_MS", 30000.0,
                    float),
                availability=paid_avail)
        # time-dimension telemetry (ISSUE 15): a registry sampler for
        # /debug/timeseries (+ exporter dumps), and — for engines — an
        # online ITL/TTFT anomaly watchdog fed at the stream edge
        self.timeseries = _ts.TimeSeriesSampler(names=SERVING_SERIES,
                                                name="serving")
        _ts.set_default_sampler(self.timeseries)
        self.anomalies = _ts.AnomalyDetector() if engine is not None \
            else None
        self._drain_timeout = drain_timeout  # None → env/default in drain()
        self._ready_window = max(1, int(ready_window))
        self._recent = []          # last ready_window predictor outcomes
        self._recent_lock = threading.Lock()
        self._shutdown_lock = threading.Lock()
        self._shutdown_done = False
        self._shutdown_complete = threading.Event()
        self._shutdown_result = True
        self._serving = False
        server = self

        class Handler(BaseHTTPRequestHandler):
            _rt_ctx = None  # the request's RequestContext (POST paths)

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code, obj, headers=()):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if self._rt_ctx is not None:
                    # EVERY response of an identified request echoes the
                    # id — a shed 429 must correlate like a 200 does
                    self.send_header("X-Request-Id",
                                     self._rt_ctx.request_id)
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    # liveness: up — even while draining (killing a
                    # draining process forfeits its in-flight work)
                    p = server._predictor
                    body = {
                        "status": "ok",
                        "inputs": (p.get_input_names()
                                   if p is not None else []),
                        "outputs": (p.get_output_names()
                                    if p is not None else []),
                        "draining": server.admission.draining,
                    }
                    if server.engine is not None:
                        body["engine"] = server.engine.stats()
                    return self._json(200, body)
                if self.path == "/ready":
                    ready, reason = server.readiness()
                    body = {"status": "ready" if ready else "not_ready",
                            "reason": reason}
                    body.update(server.admission.stats())
                    # router-relevant signals, first-class in the
                    # readiness JSON (ISSUE 9): before this they were
                    # only recoverable by parsing /metrics text.  The
                    # HTTP status semantics are unchanged — only the
                    # payload grew.
                    body["admission_limit"] = body.get("limit")
                    if server.engine is not None:
                        st = server.engine.stats()
                        body["engine"] = {
                            "batch_occupancy": st.get("occupancy"),
                            "waiting_sequences": st.get("waiting"),
                            "active_sequences": st.get("running"),
                            "max_slots": st.get("max_slots"),
                            # quantized-decode tiers (ISSUE 12): a
                            # router/operator can see which precision
                            # this replica decodes at without parsing
                            # /metrics text
                            "weight_precision":
                                st.get("weight_precision"),
                            "kv_precision": st.get("kv_precision"),
                            "spec_tokens": st.get("spec_tokens"),
                        }
                        # prefix-cache view (ISSUE 13): hit rate and
                        # cached tokens first-class in readiness, plus
                        # the physical/logical page split so a router
                        # or operator sees sharing without /metrics
                        # text parsing
                        pc = st.get("prefix_cache") or {}
                        pages = st.get("pages") or {}
                        body["engine"]["prefix_cache"] = {
                            "enabled": pc.get("enabled"),
                            "hit_rate": pc.get("hit_rate"),
                            "cached_tokens": pc.get("cached_tokens"),
                            "tokens_saved_frac":
                                pc.get("tokens_saved_frac"),
                            "shared_pages": pages.get("shared_pages"),
                            "logical_pages": pages.get("logical_pages"),
                        }
                        if server.gen_admission is not None:
                            gs = server.gen_admission.stats()
                            body["engine"]["inflight"] = gs["inflight"]
                            body["engine"]["queued"] = gs["queued"]
                    return self._json(200 if ready else 503, body)
                if self.path == "/metrics":
                    try:
                        text = server.render_metrics()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/debug/telemetry":
                    try:
                        snap = server.telemetry_snapshot()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, snap)
                if self.path == "/debug/tenants":
                    # the per-tenant table's ONLY HTTP surface: JSON
                    # here, never /metrics (a tenant id must not mint
                    # a Prometheus series — docs/OBSERVABILITY.md)
                    if server.tenant_ledger is None:
                        return self._json(
                            404, {"error": "tenant ledger disabled "
                                           "(PADDLE_TPU_TENANT_LEDGER"
                                           "=0 or metrics detached)"})
                    try:
                        body = server.tenant_ledger.snapshot()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                if self.path == "/debug/timeseries":
                    try:
                        body = server.timeseries.describe()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                if self.path == "/debug/lifecycle":
                    # this process's spawn-phase record (ISSUE 17):
                    # always answers — a replica that never went
                    # through the fleet spawn path reports its
                    # implicit anchor and whatever phases it stamped
                    try:
                        body = _lifecycle.get_ledger().record()
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    return self._json(200, body)
                if self.path.startswith("/debug/requests/"):
                    rid = self.path[len("/debug/requests/"):]
                    dbg = getattr(server.engine, "request_debug",
                                  None) if server.engine is not None \
                        else None
                    if dbg is None:
                        return self._json(
                            404, {"error": "no engine request "
                                           "timelines on this server"})
                    try:
                        body = dbg(rid)
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    if body is None:
                        return self._json(
                            404, {"error": f"unknown or aged-out "
                                           f"request id {rid!r}"})
                    return self._json(200, body)
                return self._json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path not in ("/predict", "/generate"):
                    return self._json(404, {"error": "unknown path"})
                # continue the client's identity (or mint one): id
                # echoed on every response below, context active for
                # every span/metric the request touches
                ctx = _rtrace.continue_from_headers(self.headers)
                if ctx.tenant_id is None:
                    # billing fallback chain (ISSUE 16): no X-Tenant-Id
                    # → derive a cohort key from the prefix-fingerprint
                    # routing hint (tenants sharing a prompt prefix
                    # share a bill), else `anon` — the ledger never
                    # sees an unattributed request
                    fp = self.headers.get("X-Prefix-Fingerprint")
                    tid = _tledger.sanitize_tenant(f"fp:{fp}") \
                        if fp else None
                    ctx.tenant_id = tid or _tledger.ANON_TENANT
                # QoS class resolution (ISSUE 18): an explicit valid
                # X-Priority-Class wins, else the PADDLE_TPU_QOS_CLASSES
                # tenant→class map, else the default class — resolved
                # ONCE here so admission, the engine scheduler, and the
                # SLO rows below all see the same promise
                ctx.priority_class = _qos.resolve_class(
                    tenant_id=ctx.tenant_id,
                    explicit=ctx.priority_class)
                self._rt_ctx = ctx
                with _rtrace.activate(ctx):
                    if self.path == "/generate":
                        if server.engine is None:
                            return self._json(
                                404, {"error": "no engine attached "
                                               "(generate disabled)"})
                        self._generate_traced(ctx)
                    else:
                        if server._predictor is None:
                            return self._json(
                                404, {"error": "no predictor attached "
                                               "(predict disabled)"})
                        self._predict_traced(ctx)

            def _generate_traced(self, ctx):
                """POST /generate: continuous-batching token streaming.

                Body: JSON ``{"input_ids": [ints] (one sequence),
                "max_new_tokens": int, "eos_token_id": optional int}``.
                Response: 200 + newline-delimited JSON — one
                ``{"token": t}`` line per generated token as the engine
                emits it, then a final ``{"done": true, "output_ids":
                [...], "finish_reason": ...}`` line (connection closes;
                no Content-Length — the stream IS the progress).  Sheds
                and deadline overruns map exactly like /predict
                (429/503 + Retry-After), and a client that disconnects
                mid-stream gets its sequence cancelled so its pages
                return to the pool."""
                t_req = time.perf_counter()
                sp = _trace.begin("serving.generate", cat="serving",
                                  **ctx.trace_args())
                status, slo_reason = "error", "error"
                ticket = None
                handle = None
                try:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(n) or b"{}")
                        ids = np.asarray(req["input_ids"],
                                         np.int32).reshape(-1)
                        if ids.size < 1:
                            raise ValueError("empty input_ids")
                        max_new = int(req.get("max_new_tokens", 32))
                        eos = req.get("eos_token_id")
                        # mid-stream failover resume (ISSUE 20): the
                        # router resubmits prompt+delivered under the
                        # same request id; `prebilled_tokens` marks the
                        # verify token the dead replica already billed
                        is_resume = bool(req.get("resume"))
                        prebilled = max(0, int(req.get(
                            "prebilled_tokens", 0)))
                    except Exception as e:
                        status = "client_error"
                        return self._json(
                            400, {"error": f"bad request body: "
                                           f"{type(e).__name__}: {e}"})
                    deadline = (None if server._request_timeout is None
                                else time.monotonic()
                                + server._request_timeout)
                    if ctx.deadline_ms is not None:
                        # the client's own X-Deadline-Ms: the tighter
                        # bound wins (admission refuses work it cannot
                        # finish by then, and reports shed:deadline)
                        client_dl = time.monotonic() \
                            + ctx.deadline_ms / 1e3
                        deadline = (client_dl if deadline is None
                                    else min(deadline, client_dl))
                    try:
                        with _rtrace.request_phase("admission",
                                                   endpoint="generate"):
                            ticket = server.gen_admission.admit(
                                deadline=deadline,
                                priority_class=ctx.priority_class)
                    except ShedError as e:
                        status, slo_reason = "shed", e.reason
                        return self._json(
                            e.http_status,
                            {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After",
                                      _retry_after_header(e.retry_after))])
                    _metrics.observe("serving.phase_ms",
                                     ticket.queue_wait * 1e3,
                                     phase="queue", endpoint="generate")
                    try:
                        handle = server.engine.submit(
                            ids, max_new_tokens=max_new,
                            eos_token_id=eos,
                            request_id=ctx.request_id,
                            tenant_id=ctx.tenant_id,
                            priority_class=ctx.priority_class,
                            deadline=deadline,
                            prebilled_tokens=prebilled)
                    except _DETERMINISTIC_ERRORS as e:
                        status = "client_error"
                        return self._json(
                            400, {"error": f"{type(e).__name__}: {e}"})
                    # headers INSIDE the cancel-on-disconnect guard: a
                    # client that drops before the stream starts must
                    # still free its sequence, not decode max_new
                    # tokens for a dead socket
                    try:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("X-Request-Id", ctx.request_id)
                        self.send_header("Connection", "close")
                        self.end_headers()
                        first_at = None
                        last_at = None
                        for tok in handle.stream(
                                timeout=server._request_timeout or 120.0):
                            now = time.perf_counter()
                            if last_at is not None:
                                # inter-token latency at the STREAM
                                # EDGE (ISSUE 15): what the client
                                # actually waited between tokens —
                                # queue + decode + co-scheduled work,
                                # not just the decode kernel
                                gap_ms = (now - last_at) * 1e3
                                _metrics.observe("serving.itl_ms",
                                                 gap_ms,
                                                 endpoint="generate")
                                if server.anomalies is not None:
                                    server.anomalies.observe("itl",
                                                             gap_ms)
                                if server.tenant_ledger is not None:
                                    server.tenant_ledger.observe_itl(
                                        ctx.tenant_id, gap_ms)
                            last_at = now
                            if first_at is None:
                                # time-to-first-token, labeled by the
                                # prefix-cache outcome: the histogram
                                # that shows what a warm cache buys
                                # (docs/OBSERVABILITY.md, ISSUE 13)
                                first_at = time.perf_counter()
                                ttft_ms = (first_at - t_req) * 1e3
                                cache_state = getattr(
                                    handle, "cache_state",
                                    "miss") or "miss"
                                _metrics.observe(
                                    "serving.ttft_ms", ttft_ms,
                                    endpoint="generate",
                                    # getattr: engine duck-types
                                    # (ToyEngine) may predate the
                                    # prefix cache — label them miss
                                    cache=cache_state)
                                if is_resume:
                                    # ISSUE 20 acceptance: resumed
                                    # streams should tail-prefill off
                                    # the radix index — this label is
                                    # the direct evidence (hit/partial
                                    # = the failover cost only the
                                    # uncached tail)
                                    _metrics.inc(
                                        "serving.resume_prefill",
                                        cache=cache_state)
                                _metrics.observe(
                                    "serving.phase_ms", ttft_ms,
                                    phase="first_token",
                                    endpoint="generate")
                                server.slo.observe(
                                    "ttft", ttft_ms, ok=True,
                                    cls=ctx.priority_class)
                                if server.anomalies is not None:
                                    server.anomalies.observe("ttft",
                                                             ttft_ms)
                                if server.tenant_ledger is not None:
                                    server.tenant_ledger.observe_ttft(
                                        ctx.tenant_id, ttft_ms)
                                # lifecycle (ISSUE 17): the process's
                                # first-ever emitted token closes the
                                # spawn story (quiet first-wins —
                                # concurrent streams race it
                                # legitimately)
                                _lifecycle.get_ledger().stamp_once(
                                    "first_token")
                            self.wfile.write(
                                json.dumps({"token": int(tok)}).encode()
                                + b"\n")
                            self.wfile.flush()
                        final = {
                            "done": True,
                            "request_id": handle.request_id,
                            "finish_reason": handle.finish_reason,
                            "output_ids":
                                [int(x) for x in
                                 handle.result(timeout=5.0)],
                        }
                        self.wfile.write(json.dumps(final).encode()
                                         + b"\n")
                        self.wfile.flush()
                        status = ("client_error" if handle.cancelled
                                  else "ok")
                    except (BrokenPipeError, ConnectionError, OSError):
                        # the client went away mid-stream: cancel so
                        # the sequence's pages return to the pool
                        server.engine.cancel(handle.request_id)
                        status = "client_error"
                    except queue.Empty:
                        server.engine.cancel(handle.request_id)
                        status, slo_reason = "timeout", "timeout"
                        if first_at is None:
                            # never produced a first token: that is a
                            # TTFT objective failure, not just a
                            # completion failure
                            server.slo.observe(
                                "ttft",
                                (time.perf_counter() - t_req) * 1e3,
                                ok=False, reason="timeout",
                                cls=ctx.priority_class)
                finally:
                    if ticket is not None:
                        ticket.release(ok=status == "ok")
                    dt_ms = (time.perf_counter() - t_req) * 1e3
                    if sp is not None:
                        sp.args["status"] = status
                    _trace.end(sp)
                    _metrics.observe("serving.request_ms", dt_ms,
                                     endpoint="generate", status=status)
                    _metrics.inc("serving.requests", status=status)
                    if server.tenant_ledger is not None:
                        server.tenant_ledger.record_request(
                            ctx.tenant_id, status)
                    server._slo_record(status, slo_reason, dt_ms,
                                       endpoint="generate",
                                       cls=ctx.priority_class)

            def _predict_traced(self, ctx):
                t_req = time.perf_counter()
                sp = _trace.begin("serving.request", cat="serving",
                                  **ctx.trace_args())
                status, slo_reason = "error", "error"
                try:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        raw = self.rfile.read(n)
                        with np.load(io.BytesIO(raw)) as z:
                            arrays = {k: z[k] for k in z.files}
                    except Exception as e:
                        # undecodable body: the client's fault, always
                        status = "client_error"
                        return self._json(
                            400, {"error": f"bad request body: "
                                           f"{type(e).__name__}: {e}"})
                    try:
                        outs = server.predict(arrays)
                    except ShedError as e:
                        status, slo_reason = "shed", e.reason
                        return self._json(
                            e.http_status,
                            {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After",
                                      _retry_after_header(e.retry_after))])
                    except TimeoutError as e:
                        # DeadlineExceeded is a TimeoutError subclass:
                        # the server ran out of time, not the client out
                        # of line — retryable, with a service-time hint
                        status, slo_reason = "timeout", "timeout"
                        stats = server.admission.stats()
                        hint = stats.get("ewma_latency") or 1.0
                        return self._json(
                            503, {"error": f"{type(e).__name__}: {e}"},
                            headers=[("Retry-After",
                                      _retry_after_header(hint))])
                    except _DETERMINISTIC_ERRORS as e:
                        status = "client_error"
                        return self._json(
                            400, {"error": f"{type(e).__name__}: {e}"})
                    except Exception as e:
                        return self._json(
                            500, {"error": f"{type(e).__name__}: {e}"})
                    with _rtrace.request_phase("serialize"):
                        buf = io.BytesIO()
                        np.savez(buf, **outs)
                        body = buf.getvalue()
                    status = "ok"
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("X-Request-Id", ctx.request_id)
                    self.end_headers()
                    self.wfile.write(body)
                finally:
                    dt_ms = (time.perf_counter() - t_req) * 1e3
                    if sp is not None:
                        sp.args["status"] = status
                    _trace.end(sp)
                    _metrics.observe("serving.request_ms", dt_ms,
                                     endpoint="predict", status=status)
                    _metrics.inc("serving.requests", status=status)
                    if server.tenant_ledger is not None:
                        server.tenant_ledger.record_request(
                            ctx.tenant_id, status)
                    server._slo_record(status, slo_reason, dt_ms,
                                       cls=ctx.priority_class)

        self._httpd = _ServingHTTPServer((host, port), Handler)
        self._thread = None

    @property
    def address(self):
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    # --- readiness -----------------------------------------------------------
    def readiness(self):
        """(ready, reason): not ready while draining, or when the last
        `ready_window` predictor calls ALL failed (a wedged/poisoned
        predictor should shed load balancer traffic, not collect it)."""
        if self.admission.draining:
            return False, "draining"
        with self._recent_lock:
            recent = list(self._recent)
        if len(recent) >= self._ready_window and not any(recent):
            return False, "predictor_failing"
        return True, "ok"

    def _note_outcome(self, ok):
        with self._recent_lock:
            self._recent.append(bool(ok))
            del self._recent[:-self._ready_window]

    # --- telemetry plane -----------------------------------------------------
    def _slo_record(self, status, reason, latency_ms,
                    endpoint="predict", cls=None):
        """Feed the SLO ledger with one finished request.  Client-fault
        400s (and mid-stream disconnects) are excluded — the
        availability objective is a promise about the SERVER, and one
        misbehaving client must not page the on-call for it (mirror of
        the readiness-window rule above)."""
        if status == "ok":
            self.slo.observe(endpoint, latency_ms, ok=True, cls=cls)
        elif status == "shed":
            self.slo.record_shed(endpoint, reason, cls=cls)
        elif status in ("timeout", "error"):
            self.slo.observe(endpoint, latency_ms, ok=False,
                             reason=reason, cls=cls)

    def render_metrics(self) -> str:
        """Prometheus text for GET /metrics (refreshes the slo.* gauges
        first so the scrape carries the current burn rate)."""
        self.slo.report()
        return _metrics.to_prometheus()

    def telemetry_snapshot(self) -> dict:
        """JSON body of GET /debug/telemetry: the one-stop in-process
        view — metrics snapshot, SLO report, admission stats,
        readiness, and the recent flight ring."""
        from ..observability import flight as _flight

        ready, reason = self.readiness()
        # SLO report first: it publishes the slo.* gauges the metrics
        # snapshot should carry (same ordering as the exporter)
        slo_report = self.slo.report()
        snap = {
            "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "metrics": _metrics.snapshot(),
            "slo": slo_report,
            "admission": self.admission.stats(),
            "readiness": {"ready": ready, "reason": reason},
            "flight": _flight.events()[-64:],
        }
        snap["timeseries"] = self.timeseries.stats()
        # this process's spawn-phase record (ISSUE 17) — the same body
        # GET /debug/lifecycle serves
        snap["lifecycle"] = _lifecycle.get_ledger().record()
        if self.tenant_ledger is not None:
            snap["tenants"] = self.tenant_ledger.snapshot()
        if self.anomalies is not None:
            snap["anomalies"] = self.anomalies.report()
        if self.engine is not None:
            # the engine's full view — including the prefix-cache
            # ledger and the shared/logical page split (ISSUE 13
            # satellite: page accounting stays honest under sharing)
            snap["engine"] = self.engine.stats()
            # recent per-request latency timelines (ISSUE 15): the
            # summary rows; full gap attribution lives behind
            # GET /debug/requests/<id>
            tls = getattr(self.engine, "recent_timelines", None)
            if tls is not None:
                snap["request_timelines"] = tls()
        return snap

    # --- request path --------------------------------------------------------
    def predict(self, arrays: dict) -> dict:
        p = self._predictor
        feed_order = p.get_input_names()
        if set(arrays) >= set(feed_order):
            inputs = [arrays[n] for n in feed_order]
        else:  # positional arr_0, arr_1, ... (np.savez default keys)
            inputs = [arrays[k] for k in _positional_order(arrays)]
        deadline = (None if self._request_timeout is None
                    else time.monotonic() + self._request_timeout)
        # QoS (ISSUE 18): class + client deadline ride the request
        # context — do_POST resolved the class once; direct callers
        # (tests, in-process use) resolve here from the tenant map
        ctx = _rtrace.current()
        cls = _qos.resolve_class(
            tenant_id=None if ctx is None else ctx.tenant_id,
            explicit=None if ctx is None else ctx.priority_class)
        if ctx is not None and ctx.deadline_ms is not None:
            client_dl = time.monotonic() + ctx.deadline_ms / 1e3
            deadline = (client_dl if deadline is None
                        else min(deadline, client_dl))
        # phase breakdown (ISSUE 7): "admission" spans the admit call
        # (decision + queue camp; the camp itself is the controller's
        # own nested `serving.queue` span), "queue" is observed from
        # the measured wait, "predict" spans the resilient run
        with _rtrace.request_phase("admission") as asp:
            ticket = self.admission.admit(deadline=deadline,
                                          priority_class=cls)
            if asp is not None:
                asp.args["queue_wait_ms"] = round(
                    ticket.queue_wait * 1e3, 3)
        _metrics.observe("serving.phase_ms", ticket.queue_wait * 1e3,
                         phase="queue", endpoint="predict")
        ok = None  # None = client-fault outcome: readiness unaffected
        try:
            with _rtrace.request_phase("predict"):
                outs = self._run_resilient(inputs, _deadline=deadline)
            ok = True
        except _DETERMINISTIC_ERRORS:
            # the CLIENT's request was wrong (400) — feeding this into
            # the readiness window would let one misbehaving client
            # flip a healthy server to not-ready
            raise
        except Exception:
            ok = False
            raise
        finally:
            if ok is not None:
                self._note_outcome(ok)
            ticket.release(ok=bool(ok))
        return {n: np.asarray(v)
                for n, v in zip(p.get_output_names(), outs)}

    def _run_once(self, inputs):
        from ..resilience import faults as _faults

        _faults.fire("serving.request",
                     batch=int(np.shape(inputs[0])[0])
                     if inputs and np.ndim(inputs[0]) else 0)
        with self._plock:
            return self._predictor.run(inputs)

    def _run_resilient(self, inputs, _depth=0, _deadline=None):
        """Retry, then degrade-to-smaller-batch: split the batch in half
        and serve each half independently (recursive, so a single bad
        example bounds the blast radius to itself).  `request_timeout`
        bounds the WHOLE request including the split tree — a wedged
        predictor fails the request once, not once per half."""
        import time as _time

        if _deadline is None and self._request_timeout is not None:
            _deadline = _time.monotonic() + self._request_timeout
        if _deadline is not None and _time.monotonic() > _deadline:
            raise TimeoutError(
                f"serving request exceeded its {self._request_timeout}s "
                f"deadline while degrading (depth {_depth})")
        try:
            return self._retry.call(self._run_once, inputs)
        except _DETERMINISTIC_ERRORS:
            raise  # same failure at any batch size — don't bisect
        except Exception:
            bs = {int(np.shape(x)[0]) for x in inputs if np.ndim(x) > 0}
            if _depth >= 8 or len(bs) != 1 or next(iter(bs)) < 2 or (
                    _deadline is not None
                    and _time.monotonic() > _deadline):
                raise  # nothing left to split — surface the real error
            n = next(iter(bs))
            self._note_degrade(n, _depth)

            def half(sl):
                # scalars/0-d inputs ride along unsliced
                return [x[sl] if np.ndim(x) > 0 else x for x in inputs]

            lo = self._run_resilient(half(slice(None, n // 2)),
                                     _depth + 1, _deadline)
            hi = self._run_resilient(half(slice(n // 2, None)),
                                     _depth + 1, _deadline)
            return [np.concatenate([np.asarray(a), np.asarray(b)], axis=0)
                    for a, b in zip(lo, hi)]

    @staticmethod
    def _note_degrade(batch, depth):
        try:
            from ..observability import flight as _flight
            from ..observability import metrics as _metrics

            _metrics.inc("resilience.degraded_batches")
            _flight.record("resilience.serving_degrade", batch=batch,
                           depth=depth)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: _note_degrade
            # runs inside _run_resilient's recovery handler — a
            # telemetry error escaping here would abort the
            # degrade-to-smaller-batch recursion and fail the request)

    # --- lifecycle -----------------------------------------------------------
    def start(self):
        # pt-lint: ok[PT503] (ordered flag: set True before the serving thread exists, cleared only by shutdown(); a torn read is impossible for a bool and a stale one only delays the drain a poll)
        self._serving = True  # before the thread runs: a shutdown()
        # racing start() must wait for the loop, not skip it
        if self.engine is not None:
            self.engine.start()
        self.timeseries.start()
        # pt-lint: ok[PT503] (set-once before the thread starts; shutdown() only joins it — CPython attribute store is atomic)
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True,
            name="paddle-tpu-serving")
        self._thread.start()
        return self

    def serve_forever(self):
        self._serving = True
        if self.engine is not None:
            self.engine.start()  # idempotent
        self.timeseries.start()  # idempotent
        self._httpd.serve_forever()

    def install_preemption(self, guard=None, install_signals=True):
        """Wire a `PreemptionGuard`: SIGTERM/SIGINT (or a maintenance
        event) begins the drain immediately, and the full graceful
        shutdown runs on a helper thread — `shutdown()` must never run
        inline in signal context on the thread running serve_forever()
        (it would deadlock waiting for its own loop to exit)."""
        from ..resilience.preemption import PreemptionGuard

        guard = guard or PreemptionGuard()
        if install_signals:
            guard.install()

        def _drain(reason):
            self.admission.begin_drain()  # readiness flips NOW
            threading.Thread(target=self.shutdown, daemon=True,
                             name="paddle-tpu-serving-drain").start()

        guard.on_preempt(_drain)
        self._preemption_guard = guard
        return guard

    def shutdown(self, drain_timeout=None):
        """Graceful drain: stop admitting (queued requests shed 503,
        readiness flips), finish in-flight requests up to the drain
        deadline, stop the accept loop, CLOSE the listening socket.
        Idempotent AND blocking — launcher teardown racing a signal
        handler's drain thread is the normal case, and the loser must
        WAIT for the winner's drain, not return early and let the
        process exit with requests still in flight.  Returns True when
        the drain completed before the deadline."""
        with self._shutdown_lock:
            first = not self._shutdown_done
            self._shutdown_done = True
        if not first:
            # another caller is (or was) draining: ride its result —
            # and if IT has not finished inside our wait budget, say so
            # (True here would green-light a process exit with requests
            # still in flight)
            budget = drain_timeout if drain_timeout is not None \
                else self._drain_timeout
            if budget is None:
                budget = 30.0
            finished = self._shutdown_complete.wait(
                timeout=float(budget) + 10.0)
            return bool(finished and self._shutdown_result)
        try:
            if drain_timeout is None:
                drain_timeout = self._drain_timeout
            t_drain = time.monotonic()
            drained = self.admission.drain(timeout=drain_timeout)
            if self.gen_admission is not None:
                # generate streams drain on the SAME budget, not a
                # second one: an orchestrator's kill grace period is
                # sized to one drain_timeout (PR 5 contract), so the
                # second controller gets whatever is left of it
                budget = drain_timeout if drain_timeout is not None \
                    else _env_num("PADDLE_TPU_DRAIN_TIMEOUT", 30.0,
                                  float)
                remaining = max(
                    0.0, float(budget) - (time.monotonic() - t_drain))
                drained = self.gen_admission.drain(
                    timeout=remaining) and drained
            if self.engine is not None:
                self.engine.stop()
            # one last sample so the final exporter dump carries the
            # drained end state, then stop the sampling thread
            try:
                self.timeseries.sample()
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard: shutdown
                # must never raise)
            self.timeseries.stop()
            try:
                from ..observability import flight as _flight
                from ..observability import metrics as _metrics

                _metrics.inc("preemption.drains")
                _flight.record("serving.drained", complete=bool(drained))
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard: shutdown
                # runs in signal/atexit paths and must never raise)
            if self._serving:  # shutdown() on a never-started server
                self._httpd.shutdown()  # must not block on a loop
                # that never ran
            if self._thread is not None:
                self._thread.join(timeout=5)
            # the listening socket used to leak here: without
            # server_close() the fd (and the port) stayed held for the
            # process lifetime
            self._httpd.server_close()
            self._shutdown_result = drained
        finally:
            self._shutdown_complete.set()
        return self._shutdown_result


class StreamInterrupted(RuntimeError):
    """A /generate stream was cleanly cut after tokens were already
    delivered (the serving replica died mid-stream behind a router, or
    the engine cancelled the sequence).  Carries the resumable state:
    `output_ids` is the prompt + every token delivered so far — resubmit
    it as the next request's `input_ids` to continue the generation
    without replaying a single token.  `tokens` is just the delivered
    generated tokens; `finish_reason` names the cut."""

    def __init__(self, message, output_ids=None, tokens=(),
                 finish_reason="interrupted", request_id=None,
                 tenant_id=None):
        super().__init__(message)
        self.output_ids = (None if output_ids is None
                           else np.asarray(output_ids, np.int32))
        self.tokens = list(tokens)
        self.finish_reason = finish_reason
        self.request_id = request_id
        # who was being billed when the stream cut (ISSUE 16): the
        # caller resubmitting the resumable prefix keeps ONE tenant
        # identity across the interruption
        self.tenant_id = tenant_id


class InferenceClient:
    """Protocol client with a configurable timeout and bounded retry on
    429/503 honoring the server's Retry-After header (capped at
    `max_retry_wait` so a confused server cannot park the client)."""

    def __init__(self, address: str, timeout: float = 120.0,
                 retries: int = 2, max_retry_wait: float = 5.0,
                 sleep=time.sleep, fingerprint_tokens: int = 64,
                 tenant_id=None, priority_class=None, deadline_ms=None):
        self.address = address.rstrip("/")
        self.timeout = float(timeout)
        self.retries = max(0, int(retries))
        self.max_retry_wait = float(max_retry_wait)
        self.sleep = sleep
        # billing identity (ISSUE 16): stamped as X-Tenant-Id on every
        # request this client sends.  Validated HERE, loudly — a typo'd
        # tenant silently degrading to `anon` would misbill forever.
        if tenant_id is not None \
                and _tledger.sanitize_tenant(tenant_id) is None:
            raise ValueError(
                f"invalid tenant_id {tenant_id!r}: want 1-64 chars of "
                f"[A-Za-z0-9._:-]")
        self.tenant_id = (None if tenant_id is None
                          else str(tenant_id))
        # QoS identity (ISSUE 18): stamped as X-Priority-Class /
        # X-Deadline-Ms.  Same validate-loudly rule as tenant_id — a
        # typo'd class silently degrading to the default tier would
        # mis-serve forever.
        if priority_class is not None \
                and _qos.normalize_class(priority_class) is None:
            raise ValueError(
                f"invalid priority_class {priority_class!r}: want one "
                f"of {_qos.CLASSES}")
        self.priority_class = (None if priority_class is None
                               else _qos.normalize_class(priority_class))
        self.deadline_ms = (None if deadline_ms is None
                            else int(deadline_ms))
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"invalid deadline_ms {deadline_ms!r}: want a positive "
                f"millisecond budget")
        # prefix-affinity fingerprint length (ISSUE 13): generate()
        # sends a cheap hash of the first N page-aligned prompt tokens
        # so a router can keep repeat tenants where their prefix cache
        # lives.  0 disables the header.
        self.fingerprint_tokens = max(0, int(fingerprint_tokens))

    @staticmethod
    def prefix_fingerprint(input_ids, tokens: int = 64,
                           granule: int = 16):
        """Hex fingerprint of the first `tokens` PAGE-ALIGNED prompt
        ids (floored to a `granule` multiple — the default engine page
        size — so two prompts sharing a cacheable prefix fingerprint
        alike).  Purely a ROUTING hint: the engine's radix index
        matches real token values, so a poisoned/mismatched
        fingerprint can at worst cost a cache miss, never a
        wrong-token stream.  Returns None for prompts too short to
        share a page."""
        import hashlib

        ids = np.asarray(input_ids, np.int64).reshape(-1)
        n = min(int(tokens), (ids.size // granule) * granule)
        if n <= 0:
            return None
        return hashlib.sha1(ids[:n].tobytes()).hexdigest()[:16]

    def health(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.address + "/health",
                                    timeout=self.timeout) as r:
            return json.loads(r.read())

    def ready(self) -> dict:
        """Readiness probe: {"ready": bool, ...server stats}.  A 503 is
        a VALID readiness answer, not an error."""
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(self.address + "/ready",
                                        timeout=self.timeout) as r:
                body = json.loads(r.read())
                code = r.status
        except urllib.error.HTTPError as e:
            body = json.loads(e.read() or b"{}")
            code = e.code
        body["ready"] = code == 200
        return body

    def _retry_wait(self, headers):
        """Defensive Retry-After parse (ISSUE 9 satellite): the header
        is server-controlled input that feeds straight into sleep
        math — a non-numeric value, a negative, a NaN (which poisons
        min/max comparisons and would crash time.sleep), or an absurd
        1e9 must all collapse into a bounded wait, never an exception
        and never an unbounded park.  The parsed value is clamped into
        [0, max_retry_wait]; the final wait keeps the 50 ms floor so a
        Retry-After of 0 backs off instead of busy-spinning."""
        try:
            ra = float(headers.get("Retry-After", 0.5))
        except (TypeError, ValueError):
            ra = 0.5
        if not math.isfinite(ra):
            ra = 0.5
        return min(max(ra, 0.05), self.max_retry_wait)

    def generate(self, input_ids, max_new_tokens=32, eos_token_id=None,
                 on_token=None, resume=False) -> dict:
        """Stream one sequence through POST /generate.

        Tokens are consumed INCREMENTALLY off the ndjson stream —
        `on_token(tok)` (optional) fires for each as it arrives, before
        the generation finishes.  Returns the final record:
        ``{"output_ids": np.int32 array, "tokens": [...],
        "finish_reason": ..., "request_id": ..., "resumed": n}``
        (`resumed` counts router-side mid-stream failovers this stream
        absorbed, ISSUE 20 — 0 on the common path).

        Retry discipline (ISSUE 7): ONE request identity is minted
        BEFORE the retry loop — a 429/503 shed retries under the same
        `X-Request-Id` (honoring Retry-After, capped), so server spans
        and the engine's sequence correlate every attempt.  Sheds can
        only happen before the stream starts (the status line is the
        admission decision), so retrying never replays tokens.

        With ``resume=True`` (ISSUE 20 satellite, default off): a
        `StreamInterrupted` — the router's resume-EXHAUSTED fallback —
        is absorbed by re-issuing the carried `output_ids` prefix as
        the next leg's prompt under the SAME request id, with
        `max_new_tokens` reduced by what already arrived (the greedy
        determinism contract makes the delivered tokens the prompt's
        true continuation).  Bounded by `PADDLE_TPU_STREAM_RESUME_MAX`
        legs; when the budget runs out the final `StreamInterrupted`
        propagates carrying the FULL merged token prefix."""
        ids = [int(x) for x in np.asarray(input_ids).reshape(-1)]
        max_new = int(max_new_tokens)
        amb = _rtrace.current()
        ctx = amb.child() if amb is not None else _rtrace.new_context()
        if ctx.tenant_id is None and self.tenant_id is not None:
            # one tenant identity minted BEFORE the retry loop (same
            # discipline as X-Request-Id): every attempt of one request
            # bills the same ledger row.  An ambient hop's tenant wins —
            # re-stamping mid-chain would split one request's bill.
            ctx.tenant_id = self.tenant_id
        if ctx.priority_class is None and self.priority_class is not None:
            ctx.priority_class = self.priority_class  # ambient hop wins
        if ctx.deadline_ms is None and self.deadline_ms is not None:
            ctx.deadline_ms = self.deadline_ms
        legs = (_env_num("PADDLE_TPU_STREAM_RESUME_MAX", 2, int)
                if resume else 0)
        legs_used = 0
        prior: list = []           # tokens delivered by earlier legs
        cur_ids, cur_max = ids, max_new
        while True:
            try:
                out = self._generate_attempt(cur_ids, cur_max,
                                             eos_token_id, on_token,
                                             ctx)
            except StreamInterrupted as e:
                delivered = list(e.tokens)
                if legs_used >= legs or e.output_ids is None:
                    # resume off / budget spent: surface the FULL
                    # merged resumable prefix, not just this leg's
                    e.tokens = prior + delivered
                    raise
                legs_used += 1
                prior.extend(delivered)
                cur_ids = [int(x) for x in e.output_ids]
                cur_max = cur_max - len(delivered)
                if cur_max < 1:
                    # every budgeted token already arrived; only the
                    # final record was lost — synthesize it (greedy
                    # contract: the delivered prefix IS the answer)
                    return {
                        "output_ids": np.asarray(cur_ids, np.int32),
                        "tokens": prior,
                        "finish_reason": "length",
                        "request_id": e.request_id or ctx.request_id,
                        "tenant_id": ctx.tenant_id,
                        "resumed": legs_used,
                    }
                continue
            out["tokens"] = prior + out["tokens"]
            out["resumed"] = int(out.get("resumed", 0) or 0) + legs_used
            return out

    def _generate_attempt(self, ids, max_new_tokens, eos_token_id,
                          on_token, ctx) -> dict:
        """One /generate leg under an existing request identity: the
        pre-ISSUE-20 generate() body.  Raises StreamInterrupted with
        THIS leg's delivered tokens; generate() merges legs."""
        import urllib.error
        import urllib.request

        body = {"input_ids": [int(x) for x in ids],
                "max_new_tokens": int(max_new_tokens)}
        if eos_token_id is not None:
            body["eos_token_id"] = int(eos_token_id)
        data = json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        headers.update(ctx.to_headers())
        if self.fingerprint_tokens:
            fp = self.prefix_fingerprint(body["input_ids"],
                                         self.fingerprint_tokens)
            if fp is not None:
                headers["X-Prefix-Fingerprint"] = fp
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(
                self.address + "/generate", data=data, headers=headers)
            sp = _trace.begin("client.generate", cat="client",
                             attempt=attempt, **ctx.trace_args())
            t0 = time.perf_counter()
            status = "error"
            retry_wait = None
            final = None
            try:
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout) as r:
                        tokens = []
                        for line in r:
                            line = line.strip()
                            if not line:
                                continue
                            evt = json.loads(line)
                            if evt.get("done"):
                                final = evt
                                break
                            if evt.get("interrupted"):
                                # a router cut the stream cleanly after
                                # tokens were delivered: surface the
                                # resumable prefix — NEVER silently
                                # retry (a replay would duplicate the
                                # delivered tokens)
                                status = "interrupted"
                                raise StreamInterrupted(
                                    evt.get("error",
                                            "stream interrupted"),
                                    output_ids=evt.get("output_ids"),
                                    tokens=tokens,
                                    finish_reason=evt.get(
                                        "finish_reason", "interrupted"),
                                    request_id=evt.get("request_id"),
                                    tenant_id=ctx.tenant_id)
                            tokens.append(int(evt["token"]))
                            if on_token is not None:
                                on_token(int(evt["token"]))
                    if final is None:
                        raise RuntimeError(
                            "generate stream ended without a final "
                            "record (server cancelled?)")
                    status = "ok"
                except urllib.error.HTTPError as e:
                    if e.code in (429, 503) and attempt < self.retries:
                        status = "shed_retry"
                        retry_wait = self._retry_wait(e.headers)
                    else:
                        raise
            finally:
                if sp is not None:
                    sp.args["status"] = status
                _trace.end(sp)
                _metrics.observe("client.request_ms",
                                 (time.perf_counter() - t0) * 1e3,
                                 status=status)
                _metrics.inc("client.requests", status=status)
            if retry_wait is not None:
                self.sleep(retry_wait)
                continue
            return {
                "output_ids": np.asarray(final["output_ids"], np.int32),
                "tokens": tokens,
                "finish_reason": final.get("finish_reason"),
                "request_id": final.get("request_id"),
                "tenant_id": ctx.tenant_id,
                # router-side mid-stream failovers absorbed (ISSUE 20):
                # 0 on the common path, stamped on the final record by
                # the router when a resume leg served part of the stream
                "resumed": int(final.get("resumed", 0) or 0),
            }

    def predict(self, *arrays, **named) -> dict:
        import urllib.error
        import urllib.request

        buf = io.BytesIO()
        if named:
            np.savez(buf, **named)
        else:
            np.savez(buf, *arrays)
        data = buf.getvalue()
        # ONE identity for the whole request, minted BEFORE the retry
        # loop: a 429'd request retries under the same X-Request-Id, so
        # server-side spans/logs correlate every attempt.  An ambient
        # context (this client called from inside another traced
        # request) continues as the next hop instead of starting over.
        amb = _rtrace.current()
        ctx = amb.child() if amb is not None else _rtrace.new_context()
        if ctx.tenant_id is None and self.tenant_id is not None:
            ctx.tenant_id = self.tenant_id  # one identity, all attempts
        if ctx.priority_class is None and self.priority_class is not None:
            ctx.priority_class = self.priority_class  # ambient hop wins
        if ctx.deadline_ms is None and self.deadline_ms is not None:
            ctx.deadline_ms = self.deadline_ms
        headers = {"Content-Type": "application/octet-stream"}
        headers.update(ctx.to_headers())
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(
                self.address + "/predict", data=data, headers=headers)
            sp = _trace.begin("client.predict", cat="client",
                              attempt=attempt, **ctx.trace_args())
            t0 = time.perf_counter()
            status = "error"
            payload = None
            retry_wait = None
            try:
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout) as r:
                        payload = r.read()
                    status = "ok"
                except urllib.error.HTTPError as e:
                    if e.code in (429, 503) and attempt < self.retries:
                        # the backoff sleep happens AFTER the span and
                        # latency observation close: client.request_ms
                        # measures the HTTP attempt, not the deliberate
                        # wait between attempts
                        status = "shed_retry"
                        retry_wait = self._retry_wait(e.headers)
                    else:
                        raise
            finally:
                if sp is not None:
                    sp.args["status"] = status
                _trace.end(sp)
                _metrics.observe("client.request_ms",
                                 (time.perf_counter() - t0) * 1e3,
                                 status=status)
                _metrics.inc("client.requests", status=status)
            if retry_wait is not None:
                self.sleep(retry_wait)
                continue
            with np.load(io.BytesIO(payload)) as z:
                return {k: z[k] for k in z.files}


def serve(model_path: str, host: str = "127.0.0.1", port: int = 8866):
    """Blocking entry point: `python -m paddle_tpu.inference.serving`.
    SIGTERM/SIGINT drain gracefully (finish in-flight, close the
    socket) instead of killing requests mid-predict.  With env
    `PADDLE_TPU_TELEMETRY_DIR` set, a `TelemetryExporter` dumps this
    replica's telemetry (SLO report included) periodically for
    `tools/telemetry_agg.py` to merge across the fleet."""
    srv = InferenceServer(model_path, host, port)
    guard = srv.install_preemption()
    srv.start()
    exporter = None
    if os.environ.get("PADDLE_TPU_TELEMETRY_DIR"):
        from ..observability.export import TelemetryExporter

        exporter = TelemetryExporter(
            slo=srv.slo.report,
            tenants=(srv.tenant_ledger.snapshot
                     if srv.tenant_ledger is not None else None),
            timelines=getattr(srv.engine, "recent_timelines",
                              None)).start()
    print(f"serving {model_path} at {srv.address}")
    guard.wait()           # parked until preemption/Ctrl-C
    srv.shutdown()         # idempotent with the guard's drain thread
    if exporter is not None:
        exporter.stop()    # final dump records the drained end state
    print(f"drained and stopped ({guard.reason})")


if __name__ == "__main__":
    import sys

    serve(sys.argv[1], *(sys.argv[2:] or []))
