"""HTTP inference server over the AOT predictor.

Role parity: the reference's deployment tier around `AnalysisPredictor`
(`paddle/fluid/inference/api/` + the C/Go serving surfaces and Paddle
Serving). TPU-first: the model is a saved `jit.save` export (compiled
once at load); the server is a thin host loop — request decode, one
compiled call, response encode — because XLA owns all scheduling.

Protocol (stdlib-only, zero heavy deps):
  POST /predict   body = .npz archive (numpy savez) with one array per
                  model input, keyed by feed name (or arr_0.. in feed
                  order); response = .npz with one array per fetch name.
  GET  /health    -> {"status": "ok", "inputs": [...], "outputs": [...]}

Client helper: `InferenceClient` wraps the same protocol.
"""
from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from . import Config, create_predictor

__all__ = ["InferenceServer", "InferenceClient", "serve"]

# error classes that cannot be transient: no retry, no batch bisection
_DETERMINISTIC_ERRORS = (TypeError, ValueError, KeyError, IndexError,
                         AttributeError)


class InferenceServer:
    """Serve one predictor. `start()` returns immediately (daemon thread);
    `serve_forever()` blocks. Concurrent requests serialize around the
    predictor (one device queue) via a lock.

    Resilience (docs/RESILIENCE.md): each request runs under a retry
    policy (`request_retries` attempts within the `request_timeout`
    deadline); when retries are exhausted and every input shares a
    splittable leading batch dim, the request DEGRADES — the batch is
    halved recursively (down to single items), halves run independently
    and results re-concatenate, so one poisoned/oversized example costs
    its half-batch a recompile instead of failing the whole request.
    """

    def __init__(self, model_path: str, host: str = "127.0.0.1",
                 port: int = 0, request_retries: int = 2,
                 request_timeout: float = 30.0):
        from ..resilience.retry import RetryPolicy

        cfg = Config(model_path)
        self._predictor = create_predictor(cfg)
        self._plock = threading.Lock()
        self._request_timeout = (None if request_timeout is None
                                 else float(request_timeout))
        self._retry = RetryPolicy(
            "serving", max_attempts=max(1, int(request_retries)),
            base_delay=0.01, max_delay=0.25, deadline=request_timeout,
            # deterministic request errors (wrong dtype/rank for the
            # model) fail identically on every retry AND every split —
            # surface them immediately (no retry, and _run_resilient
            # re-raises them without bisecting the batch)
            give_up_on=_DETERMINISTIC_ERRORS)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/health":
                    return self._json(404, {"error": "unknown path"})
                p = server._predictor
                self._json(200, {
                    "status": "ok",
                    "inputs": p.get_input_names(),
                    "outputs": p.get_output_names(),
                })

            def do_POST(self):
                if self.path != "/predict":
                    return self._json(404, {"error": "unknown path"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                    with np.load(io.BytesIO(raw)) as z:
                        arrays = {k: z[k] for k in z.files}
                    outs = server.predict(arrays)
                    buf = io.BytesIO()
                    np.savez(buf, **outs)
                    body = buf.getvalue()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = None

    @property
    def address(self):
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def predict(self, arrays: dict) -> dict:
        p = self._predictor
        feed_order = p.get_input_names()
        if set(arrays) >= set(feed_order):
            inputs = [arrays[n] for n in feed_order]
        else:  # positional arr_0, arr_1, ... (np.savez default keys)
            inputs = [arrays[k] for k in sorted(arrays)]
        outs = self._run_resilient(inputs)
        return {n: np.asarray(v)
                for n, v in zip(p.get_output_names(), outs)}

    def _run_once(self, inputs):
        from ..resilience import faults as _faults

        _faults.fire("serving.request",
                     batch=int(np.shape(inputs[0])[0])
                     if inputs and np.ndim(inputs[0]) else 0)
        with self._plock:
            return self._predictor.run(inputs)

    def _run_resilient(self, inputs, _depth=0, _deadline=None):
        """Retry, then degrade-to-smaller-batch: split the batch in half
        and serve each half independently (recursive, so a single bad
        example bounds the blast radius to itself).  `request_timeout`
        bounds the WHOLE request including the split tree — a wedged
        predictor fails the request once, not once per half."""
        import time as _time

        if _deadline is None and self._request_timeout is not None:
            _deadline = _time.monotonic() + self._request_timeout
        if _deadline is not None and _time.monotonic() > _deadline:
            raise TimeoutError(
                f"serving request exceeded its {self._request_timeout}s "
                f"deadline while degrading (depth {_depth})")
        try:
            return self._retry.call(self._run_once, inputs)
        except _DETERMINISTIC_ERRORS:
            raise  # same failure at any batch size — don't bisect
        except Exception:
            bs = {int(np.shape(x)[0]) for x in inputs if np.ndim(x) > 0}
            if _depth >= 8 or len(bs) != 1 or next(iter(bs)) < 2 or (
                    _deadline is not None
                    and _time.monotonic() > _deadline):
                raise  # nothing left to split — surface the real error
            n = next(iter(bs))
            self._note_degrade(n, _depth)

            def half(sl):
                # scalars/0-d inputs ride along unsliced
                return [x[sl] if np.ndim(x) > 0 else x for x in inputs]

            lo = self._run_resilient(half(slice(None, n // 2)),
                                     _depth + 1, _deadline)
            hi = self._run_resilient(half(slice(n // 2, None)),
                                     _depth + 1, _deadline)
            return [np.concatenate([np.asarray(a), np.asarray(b)], axis=0)
                    for a, b in zip(lo, hi)]

    @staticmethod
    def _note_degrade(batch, depth):
        try:
            from ..observability import flight as _flight
            from ..observability import metrics as _metrics

            _metrics.inc("resilience.degraded_batches")
            _flight.record("resilience.serving_degrade", batch=batch,
                           depth=depth)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: _note_degrade
            # runs inside _run_resilient's recovery handler — a
            # telemetry error escaping here would abort the
            # degrade-to-smaller-batch recursion and fail the request)

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="paddle-tpu-serving")
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def shutdown(self):
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)


class InferenceClient:
    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def health(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.address + "/health",
                                    timeout=30) as r:
            return json.loads(r.read())

    def predict(self, *arrays, **named) -> dict:
        import urllib.request

        buf = io.BytesIO()
        if named:
            np.savez(buf, **named)
        else:
            np.savez(buf, *arrays)
        req = urllib.request.Request(
            self.address + "/predict", data=buf.getvalue(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=120) as r:
            with np.load(io.BytesIO(r.read())) as z:
                return {k: z[k] for k in z.files}


def serve(model_path: str, host: str = "127.0.0.1", port: int = 8866):
    """Blocking entry point: `python -m paddle_tpu.inference.serving`."""
    srv = InferenceServer(model_path, host, port)
    print(f"serving {model_path} at {srv.address}")
    srv.serve_forever()


if __name__ == "__main__":
    import sys

    serve(sys.argv[1], *(sys.argv[2:] or []))
