"""HTTP inference server over the AOT predictor.

Role parity: the reference's deployment tier around `AnalysisPredictor`
(`paddle/fluid/inference/api/` + the C/Go serving surfaces and Paddle
Serving). TPU-first: the model is a saved `jit.save` export (compiled
once at load); the server is a thin host loop — request decode, one
compiled call, response encode — because XLA owns all scheduling.

Protocol (stdlib-only, zero heavy deps):
  POST /predict   body = .npz archive (numpy savez) with one array per
                  model input, keyed by feed name (or arr_0.. in feed
                  order); response = .npz with one array per fetch name.
  GET  /health    -> {"status": "ok", "inputs": [...], "outputs": [...]}

Client helper: `InferenceClient` wraps the same protocol.
"""
from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from . import Config, create_predictor

__all__ = ["InferenceServer", "InferenceClient", "serve"]


class InferenceServer:
    """Serve one predictor. `start()` returns immediately (daemon thread);
    `serve_forever()` blocks. Concurrent requests serialize around the
    predictor (one device queue) via a lock."""

    def __init__(self, model_path: str, host: str = "127.0.0.1",
                 port: int = 0):
        cfg = Config(model_path)
        self._predictor = create_predictor(cfg)
        self._plock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/health":
                    return self._json(404, {"error": "unknown path"})
                p = server._predictor
                self._json(200, {
                    "status": "ok",
                    "inputs": p.get_input_names(),
                    "outputs": p.get_output_names(),
                })

            def do_POST(self):
                if self.path != "/predict":
                    return self._json(404, {"error": "unknown path"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                    with np.load(io.BytesIO(raw)) as z:
                        arrays = {k: z[k] for k in z.files}
                    outs = server.predict(arrays)
                    buf = io.BytesIO()
                    np.savez(buf, **outs)
                    body = buf.getvalue()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = None

    @property
    def address(self):
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def predict(self, arrays: dict) -> dict:
        p = self._predictor
        feed_order = p.get_input_names()
        if set(arrays) >= set(feed_order):
            inputs = [arrays[n] for n in feed_order]
        else:  # positional arr_0, arr_1, ... (np.savez default keys)
            inputs = [arrays[k] for k in sorted(arrays)]
        with self._plock:
            outs = p.run(inputs)
        return {n: np.asarray(v)
                for n, v in zip(p.get_output_names(), outs)}

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="paddle-tpu-serving")
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def shutdown(self):
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)


class InferenceClient:
    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def health(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.address + "/health",
                                    timeout=30) as r:
            return json.loads(r.read())

    def predict(self, *arrays, **named) -> dict:
        import urllib.request

        buf = io.BytesIO()
        if named:
            np.savez(buf, **named)
        else:
            np.savez(buf, *arrays)
        req = urllib.request.Request(
            self.address + "/predict", data=buf.getvalue(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=120) as r:
            with np.load(io.BytesIO(r.read())) as z:
                return {k: z[k] for k in z.files}


def serve(model_path: str, host: str = "127.0.0.1", port: int = 8866):
    """Blocking entry point: `python -m paddle_tpu.inference.serving`."""
    srv = InferenceServer(model_path, host, port)
    print(f"serving {model_path} at {srv.address}")
    srv.serve_forever()


if __name__ == "__main__":
    import sys

    serve(sys.argv[1], *(sys.argv[2:] or []))
