"""paddle.dataset.wmt16 parity (`python/paddle/dataset/wmt16.py`):
en↔de readers over the wmt16 tar, built on `paddle_tpu.text.WMT16`."""
from __future__ import annotations

import numpy as np

from . import common
from ..text.datasets import WMT16

__all__ = []

_NAME = "wmt16.tar.gz"
_HINT = "the WMT16 en-de tarball (wmt16/{train,test,val} TSVs)"


def _archive(data_file=None):
    return common.require_local("wmt16", _NAME, _HINT, data_file)


def _reader(mode, src_dict_size, trg_dict_size, src_lang, data_file=None):
    ds = WMT16(data_file=_archive(data_file), mode=mode,
               src_dict_size=src_dict_size, trg_dict_size=trg_dict_size,
               lang=src_lang)

    def reader():
        for i in range(len(ds)):
            yield tuple(np.asarray(v) for v in ds[i])

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    """Reader of (src_ids, trg_ids, trg_ids_next) (wmt16.py:150)."""
    return _reader("train", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def test(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return _reader("test", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def validation(src_dict_size, trg_dict_size, src_lang="en",
               data_file=None):
    return _reader("val", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def get_dict(lang, dict_size, reverse=False, data_file=None):
    """Vocabulary for `lang` at `dict_size`; reverse=True returns
    id->word (wmt16.py:328)."""
    ds = WMT16(data_file=_archive(data_file), mode="train",
               src_dict_size=dict_size, trg_dict_size=dict_size,
               lang=lang)
    return ds.get_dict(lang=lang, reverse=reverse)


def fetch():
    return _archive()
