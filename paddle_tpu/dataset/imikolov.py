"""paddle.dataset.imikolov parity (`python/paddle/dataset/imikolov.py`):
PTB language-model readers with a caller-provided word_idx."""
from __future__ import annotations

import collections
import tarfile

from . import common

__all__ = []

_NAME = "simple-examples.tgz"
_HINT = "the PTB simple-examples tarball"


class DataType:
    NGRAM = 1
    SEQ = 2


def _archive(data_file=None):
    return common.require_local("imikolov", _NAME, _HINT, data_file)


def _member(tf, suffix):
    for name in tf.getnames():
        if name.endswith(suffix):
            return tf.extractfile(name)
    raise RuntimeError(f"imikolov: no member *{suffix} in archive")


def word_count(f, word_freq=None):
    """Accumulate word frequencies from a PTB file, counting <s>/<e>
    per line (imikolov.py:40)."""
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq[b"<s>"] += 1
        word_freq[b"<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50, data_file=None):
    """word -> id over train+valid with freq > min_word_freq, '<unk>'
    appended; reference drops the corpus's own '<unk>' token first
    (imikolov.py:53)."""
    with tarfile.open(_archive(data_file)) as tf:
        freq = word_count(_member(tf, "data/ptb.valid.txt"),
                          word_count(_member(tf, "data/ptb.train.txt")))
    freq.pop(b"<unk>", None)
    kept = sorted(((w, c) for w, c in freq.items() if c > min_word_freq),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w.decode(): i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def reader_creator(filename, word_idx, n, data_type, data_file=None):
    def reader():
        unk = word_idx["<unk>"]
        with tarfile.open(_archive(data_file)) as tf:
            for line in _member(tf, filename):
                words = [w.decode() for w in line.strip().split()]
                if data_type == DataType.NGRAM:
                    assert n > -1, "Invalid gram length"
                    toks = ["<s>"] + words + ["<e>"]
                    if len(toks) >= n:
                        ids = [word_idx.get(w, unk) for w in toks]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, unk) for w in words]
                    src = [word_idx["<s>"]] + ids
                    trg = ids + [word_idx["<e>"]]
                    if n <= 0 or len(src) <= n:
                        yield src, trg
                else:
                    raise ValueError(f"Unknown data type {data_type}")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM, data_file=None):
    """Reader of n-grams (NGRAM) or (src, trg) pairs (SEQ) over
    ptb.train.txt (imikolov.py:122)."""
    return reader_creator("data/ptb.train.txt", word_idx, n, data_type,
                          data_file)


def test(word_idx, n, data_type=DataType.NGRAM, data_file=None):
    return reader_creator("data/ptb.valid.txt", word_idx, n, data_type,
                          data_file)


def fetch():
    return _archive()
