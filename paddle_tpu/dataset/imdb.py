"""paddle.dataset.imdb parity (`python/paddle/dataset/imdb.py`): the
legacy reader API over the aclImdb archive (caller-provided word_idx, vs
`paddle_tpu.text.Imdb` which builds its own)."""
from __future__ import annotations

import collections
import re

from . import common
from ..text.datasets import imdb_tokenize

__all__ = []

_HINT = "aclImdb_v1.tar.gz (Stanford IMDB sentiment)"
_NAME = "aclImdb_v1.tar.gz"


def _archive(data_file=None):
    return common.require_local("imdb", _NAME, _HINT, data_file)


def tokenize(pattern, data_file=None):
    """Token lists of tar members matching `pattern` (imdb.py:38)."""
    yield from imdb_tokenize(_archive(data_file), pattern)


def build_dict(pattern, cutoff, data_file=None):
    """word -> id for words with freq > cutoff, ordered by (-freq, word),
    '<unk>' appended (imdb.py:58)."""
    freq = collections.defaultdict(int)
    for doc in tokenize(pattern, data_file):
        for w in doc:
            freq[w] += 1
    kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx, data_file=None):
    unk = word_idx["<unk>"]

    def reader():
        for doc in tokenize(pos_pattern, data_file):
            yield [word_idx.get(w, unk) for w in doc], 0
        for doc in tokenize(neg_pattern, data_file):
            yield [word_idx.get(w, unk) for w in doc], 1

    return reader


def train(word_idx, data_file=None):
    """Reader of (doc_ids, label) with label 0=pos 1=neg (imdb.py:107)."""
    return reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx, data_file)


def test(word_idx, data_file=None):
    return reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx, data_file)


def word_dict(data_file=None, cutoff=150):
    """The full-corpus dictionary at the reference's default cutoff
    (imdb.py:157)."""
    return build_dict(
        re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
        cutoff, data_file)


def fetch():
    return _archive()
