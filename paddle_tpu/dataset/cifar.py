"""paddle.dataset.cifar parity (`python/paddle/dataset/cifar.py`):
readers yielding (flattened float32 image / 255, label int)."""
from __future__ import annotations

import itertools

import numpy as np

from . import common
from ..vision.datasets import Cifar10, Cifar100

__all__ = []

_NAME10 = "cifar-10-python.tar.gz"
_NAME100 = "cifar-100-python.tar.gz"
_HINT = "the CIFAR python tarballs"


def reader_creator(filename, sub_name, cycle=False):
    """cifar.py:47 — sub_name selects the split: CIFAR-100 uses
    'train'/'test' members, CIFAR-10 'data_batch'/'test_batch' (which
    also disambiguates the loader — the file PATH may contain '100'
    without being the 100-class archive)."""
    cls = Cifar10 if "batch" in sub_name else Cifar100
    mode = "train" if "train" in sub_name or "data_batch" in sub_name \
        else "test"
    ds = cls(data_file=filename, mode=mode)

    def reader():
        it = itertools.cycle(range(len(ds))) if cycle else range(len(ds))
        for i in it:
            img, label = ds[i]
            # the Dataset item is already float32/255 CHW; the legacy
            # contract is the flattened [0,1] vector (cifar.py:47)
            yield np.asarray(img, np.float32).reshape(-1), int(label)

    return reader


def train100(data_file=None):
    return reader_creator(
        common.require_local("cifar", _NAME100, _HINT, data_file), "train")


def test100(data_file=None):
    return reader_creator(
        common.require_local("cifar", _NAME100, _HINT, data_file), "test")


def train10(cycle=False, data_file=None):
    return reader_creator(
        common.require_local("cifar", _NAME10, _HINT, data_file),
        "data_batch", cycle=cycle)


def test10(cycle=False, data_file=None):
    return reader_creator(
        common.require_local("cifar", _NAME10, _HINT, data_file),
        "test_batch", cycle=cycle)


def fetch():
    return (common.require_local("cifar", _NAME10, _HINT),
            common.require_local("cifar", _NAME100, _HINT))
