"""paddle.dataset parity (`python/paddle/dataset/`): the legacy
reader-creator dataset namespace (still public in the reference's
top-level import). Each module reads a LOCAL copy of its official
archive from DATA_HOME (`common.DATA_HOME`; `PADDLE_TPU_DATA_HOME`
overrides) or an explicit `data_file=` — this build has no network
egress, so nothing auto-downloads. The modern tier is
`paddle_tpu.vision.datasets` / `paddle_tpu.text` / `paddle_tpu.audio`.
"""
from . import (  # noqa: F401
    cifar, common, conll05, flowers, image, imdb, imikolov, mnist,
    movielens, uci_housing, voc2012, wmt14, wmt16,
)

__all__ = []
