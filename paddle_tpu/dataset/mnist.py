"""paddle.dataset.mnist parity (`python/paddle/dataset/mnist.py`): IDX
readers yielding (image [784] float32 in [-1, 1], label int64)."""
from __future__ import annotations

import numpy as np

from . import common
from ..vision.datasets import MNIST

__all__ = []

_FILES = {
    "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
    "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
}


def _paths(mode):
    img, lab = _FILES[mode]
    return (common.require_local("mnist", img, "the MNIST IDX archives"),
            common.require_local("mnist", lab, "the MNIST IDX archives"))


def reader_creator(image_filename, label_filename, buffer_size=None):
    """mnist.py:42 — images scaled to [-1, 1] float32, flattened."""
    import os

    for p in (image_filename, label_filename):
        if not os.path.exists(p):
            # the vision MNIST class falls back to synthetic digits for
            # missing paths (its documented CI behavior); the legacy
            # reader must raise like the reference would on open
            raise FileNotFoundError(f"mnist: no such IDX file: {p}")
    ds = MNIST(image_path=image_filename, label_path=label_filename)

    def reader():
        for i in range(len(ds)):
            img = ds.images[i].reshape(-1).astype(np.float32)
            yield img / 127.5 - 1.0, int(ds.labels[i])

    return reader


def _resolve(mode, image_path, label_path):
    if image_path is None and label_path is None:
        return _paths(mode)
    if image_path is None or label_path is None:
        raise ValueError(
            "mnist: pass BOTH image_path and label_path (or neither, to "
            "use DATA_HOME) — defaulting just one would silently pair "
            "mismatched files")
    return image_path, label_path


def train(image_path=None, label_path=None):
    return reader_creator(*_resolve("train", image_path, label_path))


def test(image_path=None, label_path=None):
    return reader_creator(*_resolve("test", image_path, label_path))


def fetch():
    return _paths("train") + _paths("test")
