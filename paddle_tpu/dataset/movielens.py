"""paddle.dataset.movielens parity (`python/paddle/dataset/
movielens.py`): ml-1m readers + metadata queries, built on
`paddle_tpu.text.Movielens`'s parser."""
from __future__ import annotations

import re

import numpy as np

from . import common
from ..text.datasets import Movielens, MovieInfo, UserInfo  # noqa: F401

__all__ = []

_NAME = "ml-1m.zip"
_HINT = "the MovieLens ml-1m zip"

age_table = [1, 18, 25, 35, 45, 50, 56]


def _archive(data_file=None):
    return common.require_local("movielens", _NAME, _HINT, data_file)


def _dataset(mode="train", data_file=None, **kw):
    return Movielens(data_file=_archive(data_file), mode=mode, **kw)


def __reader_creator__(mode, data_file=None, **kwargs):
    ds = _dataset(mode, data_file, **kwargs)

    def reader():
        for i in range(len(ds)):
            yield tuple(np.asarray(v) for v in ds[i])

    return reader


def train(data_file=None):
    return __reader_creator__("train", data_file)


def test(data_file=None):
    return __reader_creator__("test", data_file)


def get_movie_title_dict(data_file=None):
    """word -> id over movie titles (movielens.py:194)."""
    return _dataset(data_file=data_file).movie_title_dict


def movie_categories(data_file=None):
    """category -> id (movielens.py:266)."""
    return _dataset(data_file=data_file).categories_dict


def max_movie_id(data_file=None):
    return max(_dataset(data_file=data_file).movie_info)


def max_user_id(data_file=None):
    return max(_dataset(data_file=data_file).user_info)


def max_job_id(data_file=None):
    return max(int(u.job_id)
               for u in _dataset(data_file=data_file)
               .user_info.values())


def movie_info(data_file=None):
    """movie id -> MovieInfo (movielens.py:294)."""
    return _dataset(data_file=data_file).movie_info


def user_info(data_file=None):
    """user id -> UserInfo (movielens.py:280)."""
    return _dataset(data_file=data_file).user_info


def fetch():
    return _archive()
