"""paddle.dataset.flowers parity (`python/paddle/dataset/flowers.py`):
Oxford-102 readers; mapper applied via paddle_tpu.reader pipelines."""
from __future__ import annotations

import itertools

import numpy as np

from . import common
from .. import reader as reader_mod
from ..vision.datasets import Flowers

__all__ = []

_HINT = "102flowers.tgz + imagelabels.mat + setid.mat"


def default_mapper(is_train, sample):
    """Identity-with-layout mapper: the Dataset class already decodes;
    reference flowers.py:58 resizes/crops via paddle.dataset.image."""
    img, label = sample
    return np.asarray(img), int(np.asarray(label).ravel()[0])


train_mapper = lambda sample: default_mapper(True, sample)   # noqa: E731
test_mapper = lambda sample: default_mapper(False, sample)   # noqa: E731


def _dataset(mode, data_file=None, label_file=None, setid_file=None):
    return Flowers(
        data_file=common.require_local("flowers", "102flowers.tgz",
                                       _HINT, data_file),
        label_file=common.require_local("flowers", "imagelabels.mat",
                                        _HINT, label_file),
        setid_file=common.require_local("flowers", "setid.mat", _HINT,
                                        setid_file),
        mode=mode, download=False)


def reader_creator(data_file, label_file, setid_file, dataset_name,
                   mapper, buffered_size=1024, use_xmap=True,
                   cycle=False):
    # reference flag swap (flowers.py:53): TRAIN_FLAG='tstid' (the larger
    # split trains); the vision class mode names already encode the swap
    mode = {"tstid": "train", "trnid": "test", "valid": "valid"}.get(
        dataset_name, dataset_name)
    ds = _dataset(mode, data_file, label_file, setid_file)

    def base_reader():
        it = itertools.cycle(range(len(ds))) if cycle else range(len(ds))
        for i in it:
            yield ds[i]

    if use_xmap:
        return reader_mod.xmap_readers(mapper, base_reader, 4,
                                       buffered_size)
    return reader_mod.map_readers(mapper, base_reader)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True,
          cycle=False):
    return reader_creator(None, None, None, "tstid", mapper,
                          buffered_size, use_xmap, cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True,
         cycle=False):
    return reader_creator(None, None, None, "trnid", mapper,
                          buffered_size, use_xmap, cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return reader_creator(None, None, None, "valid", mapper,
                          buffered_size, use_xmap)


def fetch():
    return (common.require_local("flowers", "102flowers.tgz", _HINT),
            common.require_local("flowers", "imagelabels.mat", _HINT),
            common.require_local("flowers", "setid.mat", _HINT))
