"""paddle.dataset.image parity (`python/paddle/dataset/image.py`):
numpy/PIL image helpers for the legacy reader pipelines (the reference
uses cv2; PIL is this build's decoder — same semantics, HWC uint8 in,
documented layouts out)."""
from __future__ import annotations

import io

import numpy as np

__all__ = []


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "paddle_tpu.dataset.image needs Pillow for decoding") from e
    return Image


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded image from bytes (image.py role): HWC uint8
    (RGB) or HW (grayscale)."""
    img = _pil().open(io.BytesIO(bytes_))
    return np.asarray(img.convert("RGB" if is_color else "L"))


def load_image(file_path, is_color=True):
    with open(file_path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def resize_short(im, size):
    """Resize so the SHORter edge equals `size`, keeping aspect."""
    h, w = im.shape[:2]
    if h < w:
        new_h, new_w = size, int(round(w * size / h))
    else:
        new_h, new_w = int(round(h * size / w)), size
    pil_img = _pil().fromarray(im)
    return np.asarray(pil_img.resize((new_w, new_h)))


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (image.py to_chw)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """resize_short -> crop (random+flip when training, center else) ->
    CHW float32, optionally mean-subtracted (image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and len(im.shape) == 3:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
