"""paddle.dataset.conll05 parity (`python/paddle/dataset/conll05.py`):
SRL test-split reader + dict/embedding accessors, built on
`paddle_tpu.text.Conll05st`."""
from __future__ import annotations

import numpy as np

from . import common
from ..text.datasets import Conll05st

__all__ = []

_FILES = {
    "data_file": ("conll05st-tests.tar.gz", "the CoNLL-2005 test tar"),
    "word_dict_file": ("wordDict.txt", "the CoNLL word dict"),
    "verb_dict_file": ("verbDict.txt", "the CoNLL verb dict"),
    "target_dict_file": ("targetDict.txt", "the CoNLL target dict"),
}


def _dataset(emb_file=None, **overrides):
    kw = {}
    for key, (name, hint) in _FILES.items():
        kw[key] = common.require_local("conll05", name, hint,
                                       overrides.get(key))
    if emb_file is None:
        p = common.local_path("conll05", "emb")
        import os

        emb_file = p if os.path.exists(p) else None
    return Conll05st(emb_file=emb_file, **kw)


def get_dict(**overrides):
    """(word_dict, verb_dict, label_dict) (conll05.py:207)."""
    return _dataset(**overrides).get_dict()


def get_embedding(emb_file=None, **overrides):
    """Path of the pretrained embedding file (conll05.py:229)."""
    return _dataset(emb_file=emb_file, **overrides).get_embedding()


def test(**overrides):
    """Reader over the WSJ test split: 9-tuples of per-token index
    sequences (conll05.py:242)."""
    ds = _dataset(**overrides)

    def reader():
        for i in range(len(ds)):
            yield tuple(np.asarray(v) for v in ds[i])

    return reader


def fetch():
    return tuple(common.require_local("conll05", name, hint)
                 for name, hint in _FILES.values())
