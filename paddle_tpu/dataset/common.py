"""paddle.dataset.common parity (`python/paddle/dataset/common.py`):
DATA_HOME, archive lookup, md5, reader splitting. Zero-egress build:
`download()` never fetches — it verifies a pre-placed local copy under
DATA_HOME and raises with instructions otherwise (the same contract as
`paddle_tpu.text.datasets`)."""
from __future__ import annotations

import glob
import hashlib
import os
import pickle

__all__ = []

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "dataset"))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    """Resolve the local copy of `url` under DATA_HOME/module_name (this
    build has no network egress — reference common.py:73 would fetch).
    Raises with placement instructions when the file is absent or fails
    the md5 check."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1].split("?")[0])
    if not os.path.exists(filename):
        raise RuntimeError(
            f"no network egress in this build: place the archive from "
            f"{url} at {filename} (or set PADDLE_TPU_DATA_HOME)")
    if md5sum and md5file(filename) != md5sum:
        raise RuntimeError(
            f"{filename} exists but fails its md5 check ({md5sum}); "
            f"re-obtain the archive from {url}")
    return filename


def local_path(module_name, filename):
    """DATA_HOME/module_name/filename (no existence check)."""
    return os.path.join(DATA_HOME, module_name, filename)


def require_local(module_name, filename, hint, override=None):
    """The archive for a dataset module: `override` if given, else the
    DATA_HOME location; raises with placement guidance when absent."""
    path = override or local_path(module_name, filename)
    if not os.path.exists(path):
        raise RuntimeError(
            f"paddle_tpu.dataset.{module_name}: archive not found at "
            f"{path} (no network egress in this build). Obtain {hint} "
            f"and place it there, set PADDLE_TPU_DATA_HOME, or pass "
            f"data_file= explicitly.")
    return path


def fetch_all():
    raise RuntimeError(
        "fetch_all() downloads every corpus — unsupported in this "
        "zero-egress build; place archives under DATA_HOME instead "
        f"({DATA_HOME})")


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split a reader's samples into chunked files of `line_count` each
    (reference common.py:146). Returns the number of files written."""
    if not callable(reader):
        raise TypeError("reader should be callable")
    if "%" not in suffix:
        raise ValueError("suffix should contain %d")
    lines = []
    indx_f = 0
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)
        indx_f += 1
    return indx_f


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Reader over this trainer's shard of the chunked files produced by
    `split` (reference common.py:184)."""

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [f for i, f in enumerate(file_list)
                    if i % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, "rb") as f:
                for item in loader(f):
                    yield item

    return reader


def _check_exists_and_download(path, url, md5, module_name, download_=True):
    """Reference `_check_exists_and_download` role: path if it exists,
    else the DATA_HOME copy (never a network fetch here)."""
    if path and os.path.exists(path):
        return path
    if download_:
        return download(url, module_name, md5)
    raise ValueError(f"{path} not exists and auto download disabled")
