"""paddle.dataset.wmt14 parity (`python/paddle/dataset/wmt14.py`):
en→fr readers over the preprocessed tar (src.dict/trg.dict inside),
built on `paddle_tpu.text.WMT14`."""
from __future__ import annotations

import numpy as np

from . import common
from ..text.datasets import WMT14

__all__ = []

_NAME = "wmt14.tgz"
_HINT = "the preprocessed WMT14 en-fr tarball"


def _archive(data_file=None):
    return common.require_local("wmt14", _NAME, _HINT, data_file)


def _reader(mode, dict_size, data_file=None):
    ds = WMT14(data_file=_archive(data_file), mode=mode,
               dict_size=dict_size)

    def reader():
        for i in range(len(ds)):
            yield tuple(np.asarray(v) for v in ds[i])

    return reader


def train(dict_size, data_file=None):
    """Reader of (src_ids, trg_ids, trg_ids_next) (wmt14.py:120)."""
    return _reader("train", dict_size, data_file)


def test(dict_size, data_file=None):
    return _reader("test", dict_size, data_file)


def gen(dict_size, data_file=None):
    return _reader("gen", dict_size, data_file)


def get_dict(dict_size, reverse=True, data_file=None):
    """(src_dict, trg_dict); reverse=True returns id->word
    (wmt14.py:182)."""
    ds = WMT14(data_file=_archive(data_file), mode="train",
               dict_size=dict_size)
    src, trg = ds.get_dict(reverse=False)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def fetch():
    return _archive()
