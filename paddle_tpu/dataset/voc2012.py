"""paddle.dataset.voc2012 parity (`python/paddle/dataset/voc2012.py`):
segmentation readers yielding (image CHW, label HW)."""
from __future__ import annotations

import numpy as np

from . import common
from ..vision.datasets import VOC2012

__all__ = []

_NAME = "VOCtrainval_11-May-2012.tar"
_HINT = "the VOC2012 trainval tar"


def reader_creator(filename, sub_name):
    ds = VOC2012(data_file=filename, mode=sub_name, download=False)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            yield np.asarray(img), np.asarray(label)

    return reader


def train(data_file=None):
    return reader_creator(
        common.require_local("voc2012", _NAME, _HINT, data_file), "train")


def test(data_file=None):
    return reader_creator(
        common.require_local("voc2012", _NAME, _HINT, data_file), "test")


def val(data_file=None):
    return reader_creator(
        common.require_local("voc2012", _NAME, _HINT, data_file), "valid")


def fetch():
    return common.require_local("voc2012", _NAME, _HINT)
