"""paddle.dataset.uci_housing parity (`python/paddle/dataset/
uci_housing.py`): Boston-housing readers over the whitespace-float file,
mean-normalized features, 80/20 split."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []

_NAME = "housing.data"
_HINT = "the UCI Boston housing.data file"

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None
_LOADED_FILE = None


def _archive(data_file=None):
    return common.require_local("uci_housing", _NAME, _HINT, data_file)


def feature_range(maximums, minimums):  # plotting hook in the reference
    return None


def load_data(filename, feature_num=14, ratio=0.8):
    """Populate the train/test splits (uci_housing.py:80). The cache is
    keyed by filename — a different data_file reloads rather than
    silently serving the previous file's splits."""
    global UCI_TRAIN_DATA, UCI_TEST_DATA, _LOADED_FILE
    if UCI_TRAIN_DATA is not None and UCI_TEST_DATA is not None \
            and _LOADED_FILE == filename:
        return
    _LOADED_FILE = filename
    data = np.fromfile(filename, sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums, minimums = data.max(axis=0), data.min(axis=0)
    avgs = data.mean(axis=0)
    feature_range(maximums[:-1], minimums[:-1])
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    UCI_TRAIN_DATA = data[:offset]
    UCI_TEST_DATA = data[offset:]


def _reader_creator(split_name, data_file):
    def reader():
        load_data(_archive(data_file))
        rows = UCI_TRAIN_DATA if split_name == "train" else UCI_TEST_DATA
        for row in rows:
            yield row[:-1], row[-1:]

    return reader


def train(data_file=None):
    """Reader of (features [13] f64, price [1]) (uci_housing.py:107)."""
    return _reader_creator("train", data_file)


def test(data_file=None):
    return _reader_creator("test", data_file)


def predict_reader(data_file=None):
    """First 100 test samples, features only (uci_housing.py:171)."""
    def reader():
        load_data(_archive(data_file))
        for row in UCI_TEST_DATA[:100]:
            yield (row[:-1],)

    return reader


def fetch():
    return _archive()
