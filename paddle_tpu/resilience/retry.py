"""Reusable retry policy: exponential backoff + seeded jitter, deadlines,
and a circuit breaker.

One policy object serves every transient-failure surface in the stack —
eager collectives (`distributed/collective.py`), the elastic manager's
TCPStore heartbeat traffic, and serving request handling — so retry
behavior is tuned (and observed: `resilience.retries{policy=...}` /
`resilience.giveups{policy=...}` counters + flight events) in one place.

Determinism: jitter draws come from a `random.Random` seeded per policy,
and both the sleep and the clock are injectable — tests run the full
backoff schedule without wall-clock waits.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["RetryPolicy", "CircuitBreaker", "CircuitOpenError",
           "DeadlineExceeded", "retrying", "env_policy"]


class CircuitOpenError(RuntimeError):
    """Raised without attempting the call while the breaker is open."""


class DeadlineExceeded(TimeoutError):
    """The policy's total deadline elapsed before a retry could run.
    `__cause__` carries the last real failure."""


class CircuitBreaker:
    """Classic closed → open → half-open breaker.

    After `failure_threshold` CONSECUTIVE failures the breaker opens:
    calls fail fast with `CircuitOpenError` (no load on the failing
    dependency) until `reset_timeout` passes, then exactly one trial
    call is admitted (half-open); its success closes the breaker, its
    failure re-opens it for another window.
    """

    def __init__(self, failure_threshold=5, reset_timeout=30.0,
                 clock=time.monotonic, name="circuit"):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self.clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at = None
        self._half_open_inflight = False

    @property
    def state(self):
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self.clock() - self._opened_at >= self.reset_timeout:
                return "half_open"
            return "open"

    def allow(self):
        """Admit or refuse one call attempt (refusal raises)."""
        with self._lock:
            if self._opened_at is None:
                return
            if self.clock() - self._opened_at < self.reset_timeout:
                raise CircuitOpenError(
                    f"circuit {self.name!r} open "
                    f"({self._failures} consecutive failures)")
            # half-open: admit a single trial; concurrent callers keep
            # failing fast until the trial resolves
            if self._half_open_inflight:
                raise CircuitOpenError(
                    f"circuit {self.name!r} half-open trial in flight")
            self._half_open_inflight = True

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._half_open_inflight = False

    def record_failure(self):
        """Returns True when this failure OPENED the breaker (edge)."""
        with self._lock:
            self._failures += 1
            self._half_open_inflight = False
            was_open = self._opened_at is not None
            if self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                return not was_open
            return False


class RetryPolicy:
    """Call wrapper with bounded exponential backoff.

    delay(attempt k) = min(max_delay, base_delay * multiplier**(k-1))
                       * (1 + jitter * U[-1, 1))           (seeded)

    `deadline` bounds the TOTAL wall time across attempts: when the next
    backoff would land past it, the policy raises `DeadlineExceeded`
    from the last real error instead of sleeping.  `retry_on` /
    `give_up_on` are exception-class filters (give_up wins).
    """

    def __init__(self, name, max_attempts=3, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.25, deadline=None,
                 retry_on=(Exception,), give_up_on=(), seed=None,
                 sleep=time.sleep, clock=time.monotonic,
                 circuit_breaker=None):
        import random

        self.name = str(name)
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self.retry_on = tuple(retry_on)
        self.give_up_on = tuple(give_up_on)
        self.sleep = sleep
        self.clock = clock
        self.breaker = circuit_breaker
        base = int(seed if seed is not None
                   else os.environ.get("PADDLE_TPU_RETRY_SEED", "0"))
        import zlib

        self._rng = random.Random(
            (base * 1000003) ^ zlib.crc32(self.name.encode()))
        self._rng_lock = threading.Lock()

    def backoff(self, attempt):
        """Deterministic-given-seed delay before retry number `attempt`
        (1-based: the delay after the attempt-th failure)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            with self._rng_lock:
                d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def call(self, fn, *args, **kwargs):
        """Run `fn` under this policy.  Non-retryable errors and the
        final failure propagate unchanged (CI stack traces point at the
        real fault, not the retry machinery)."""
        start = self.clock()
        last = None
        for attempt in range(1, self.max_attempts + 1):
            if self.breaker is not None:
                self.breaker.allow()  # raises CircuitOpenError fast
            try:
                out = fn(*args, **kwargs)
            except self.give_up_on:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            except self.retry_on as e:
                last = e
                opened = (self.breaker.record_failure()
                          if self.breaker is not None else False)
                if opened:
                    self._count("resilience.circuit_open")
                    self._note("resilience.circuit_opened", attempt, e)
                if attempt >= self.max_attempts:
                    break
                delay = self.backoff(attempt)
                if self.deadline is not None and \
                        self.clock() - start + delay > self.deadline:
                    self._note("resilience.retry_deadline", attempt, e)
                    raise DeadlineExceeded(
                        f"policy {self.name!r}: deadline "
                        f"{self.deadline}s exhausted after {attempt} "
                        f"attempts") from e
                self._note("resilience.retry", attempt, e, delay=delay)
                self._count("resilience.retries")
                self.sleep(delay)
            else:
                if self.breaker is not None:
                    self.breaker.record_success()
                return out
        self._count("resilience.giveups")
        self._note("resilience.retry_giveup", self.max_attempts, last)
        raise last

    def __call__(self, fn):
        """Use a policy instance as a decorator."""
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            return self.call(fn, *a, **kw)

        wrapped.retry_policy = self
        return wrapped

    # --- observability (never lets telemetry break the retried path) ---
    def _count(self, counter):
        try:
            from ..observability import metrics as _metrics

            _metrics.inc(counter, policy=self.name)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: telemetry
            # failing must never break the retried path, and there is
            # no channel left to report the telemetry failure on)

    def _note(self, kind, attempt, err, **extra):
        try:
            from ..observability import flight as _flight

            _flight.record(kind, policy=self.name, attempt=attempt,
                           error=f"{type(err).__name__}: {err}", **extra)
        except Exception:  # pt-lint: ok[PT005] (fan-out guard, as above)
            pass


def retrying(name, **policy_kwargs):
    """Decorator factory: `@retrying("io.read", max_attempts=5)`."""
    return RetryPolicy(name, **policy_kwargs)


_env_policies: dict = {}
_env_policies_lock = threading.Lock()


def env_policy(name, env_var, default_attempts, **kwargs):
    """Process-wide RetryPolicy singleton with `max_attempts` read from
    `env_var` — the one factory behind the wired-in policies
    (collective dispatch, dataloader fetch, jit compile), so tuning
    lives here instead of three copy-pasted lazy-global blocks."""
    # double-checked locking: lock-free first probe is a GIL-atomic
    # dict get; a stale miss just re-checks under the lock
    pol = _env_policies.get(name)  # pt-lint: ok[PT102]
    if pol is None:
        with _env_policies_lock:
            pol = _env_policies.get(name)
            if pol is None:
                pol = RetryPolicy(
                    name,
                    max_attempts=int(os.environ.get(
                        env_var, str(default_attempts))),
                    **kwargs)
                _env_policies[name] = pol
    return pol
