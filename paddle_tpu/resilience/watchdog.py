"""Heartbeat hang watchdog: detects a stalled training/serving loop and
dumps the evidence BEFORE the process is killed from outside.

A stalled collective or a wedged host loop looks identical from the
orchestrator: no step progress.  The watchdog turns that into a
diagnosable event — on stall it dumps the PR-1 flight ring and exports
the PR-2 Perfetto trace (the last thing every subsystem decided), bumps
`resilience.watchdog_trips`, runs the `on_stall` callback, and (when
`raise_in_main=True`) interrupts the main thread so the run dies with a
stack trace at the stall point instead of hanging until preemption.

Feeding: `watch_step_timer()` hooks `observability.step_stats` so every
StepTimer record beats the watchdog (zero changes at call sites), and
`beat()` is public for manual loops.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["Watchdog", "WatchdogStall"]


class WatchdogStall(RuntimeError):
    pass


class Watchdog:
    def __init__(self, timeout=60.0, poll=None, on_stall=None,
                 dump_dir=None, raise_in_main=False, clock=time.monotonic,
                 name="train"):
        self.timeout = float(timeout)
        self.poll = float(poll) if poll is not None \
            else max(0.05, self.timeout / 10.0)
        self.on_stall = on_stall
        self.dump_dir = dump_dir
        self.raise_in_main = bool(raise_in_main)
        self.clock = clock
        self.name = str(name)
        self._lock = threading.Lock()
        self._last_beat = None
        self._stop = threading.Event()
        self._thread = None
        self._hook = None
        self.trips = 0
        self.last_dump = None

    # --- heartbeat ----------------------------------------------------------
    def beat(self):
        with self._lock:
            self._last_beat = self.clock()

    def watch_step_timer(self):
        """Beat on every StepTimer record (train/serve/bench loops feed
        the watchdog for free).  Returns self for chaining."""
        from ..observability import step_stats

        if self._hook is None:
            self._hook = lambda rec: self.beat()
            step_stats.add_record_hook(self._hook)
        return self

    # --- lifecycle (start/stop idempotent) ----------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._last_beat = self.clock()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"resilience-watchdog-{self.name}")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # the handle swap happens under the same lock start() uses:
        # stop() racing start() must never join a thread start() is
        # still publishing (PT101 — the race the lint gate now catches)
        with self._lock:
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        with self._lock:
            # only retire the thread we stopped: a start() that ran
            # between the two lock sections published a NEW watchdog
            # that must not be orphaned here
            if self._thread is t:
                self._thread = None
        if self._hook is not None:
            try:
                from ..observability import step_stats

                step_stats.remove_record_hook(self._hook)
            except Exception:
                from ..observability import metrics as _metrics

                _metrics.inc("resilience.watchdog_unhook_errors")
            self._hook = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --- stall detection ----------------------------------------------------
    def stalled_for(self):
        with self._lock:
            if self._last_beat is None:
                return 0.0
            return self.clock() - self._last_beat

    def _run(self):
        while not self._stop.wait(self.poll):
            age = self.stalled_for()
            if age > self.timeout:
                self._trip(age)
                # re-arm: a recovered loop (e.g. rollback + restart)
                # should be watchable again without a new Watchdog
                self.beat()

    def check(self):
        """Synchronous probe for host loops that poll instead of running
        the thread: raises WatchdogStall past the timeout."""
        age = self.stalled_for()
        if age > self.timeout:
            self._trip(age)
            raise WatchdogStall(
                f"watchdog {self.name!r}: no heartbeat for {age:.1f}s "
                f"(timeout {self.timeout}s)")

    def _trip(self, age):
        # pt-lint: ok[PT503] (monitoring counter: incremented by whichever thread detects the stall; a torn read is impossible for an int and a lost increment only undercounts evidence files)
        self.trips += 1
        dump_path = trace_path = None
        try:
            from ..observability import flight as _flight
            from ..observability import metrics as _metrics
            from ..observability import trace as _trace

            _metrics.inc("resilience.watchdog_trips")
            _flight.record("resilience.watchdog_trip", watchdog=self.name,
                           stalled_s=round(age, 3), timeout_s=self.timeout)
            import tempfile

            # default to tmp, not CWD: stall evidence must not litter
            # whatever directory the job happens to be running in
            d = self.dump_dir or os.environ.get(
                "PADDLE_TPU_WATCHDOG_DIR", tempfile.gettempdir())
            os.makedirs(d, exist_ok=True)
            tag = f"watchdog_{self.name}_{os.getpid()}_{self.trips}"
            dump_path = _flight.dump(os.path.join(d, tag + "_flight.jsonl"),
                                     reason=f"watchdog_stall:{age:.1f}s")
            if _trace.enabled() and _trace.events():
                trace_path = os.path.join(d, tag + "_trace.json")
                _trace.export(trace_path)
        except Exception as e:
            # evidence collection must never mask the stall — but a
            # silent evidence failure is its own black hole: say so on
            # stderr (the one channel that cannot have been the thing
            # that just failed)
            import sys

            print(f"[resilience] watchdog evidence dump failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        # pt-lint: ok[PT503] (monitoring breadcrumb: single atomic tuple store, read only by humans/tests asking "where did the evidence go")
        self.last_dump = (dump_path, trace_path)
        if self.on_stall is not None:
            try:
                self.on_stall(age)
            except Exception:
                try:
                    from ..observability import metrics as _metrics

                    _metrics.inc("resilience.watchdog_callback_errors")
                except Exception:  # pt-lint: ok[PT005] (observability
                    pass           # fan-out guard: nothing left to tell)
        if self.raise_in_main:
            import _thread

            _thread.interrupt_main()
