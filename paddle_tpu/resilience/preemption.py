"""Preemption-safe shutdown: SIGTERM/SIGINT guard + maintenance hook
(docs/RESILIENCE.md).

Preemptible TPU VMs get a SIGTERM and a short grace window before the
machine disappears; maintenance events announce the same thing through
a metadata endpoint.  Both used to be process death: the signal either
killed Python outright or hit `ElasticManager.signal_handler`'s
`os._exit`, vanishing mid-collective with unsaved optimizer state and
in-flight serving requests.

`PreemptionGuard` turns the signal into a *cooperative* shutdown:

  * `install()` replaces the SIGTERM/SIGINT handlers with one that only
    TRIPS the guard (sets an event, counts the signal, fires registered
    callbacks) — no work happens in signal context beyond flag flips.
  * long-running loops poll `guard.check()` at their own safe points:
    the training step checkpoints through its `CheckpointManager` and
    raises `TrainingPreempted`; the serving loop flips to draining and
    exits after in-flight requests finish; the elastic manager stops
    heartbeating so the rank ages out of membership instead of holding
    a fresh beat while dead.
  * a pollable `maintenance_hook` (any callable returning truthy when a
    maintenance/preemption event is pending — e.g. a reader of the GCE
    metadata endpoint) feeds the same trip path, rate-limited to
    `maintenance_interval` seconds between polls.

The guard trips once: the first reason wins, later signals are counted
but do not re-fire callbacks.  `uninstall()` restores the previous
handlers (tests, nested runners).
"""
from __future__ import annotations

import signal
import threading
import time

__all__ = ["PreemptionGuard", "TrainingPreempted"]


class TrainingPreempted(Exception):
    """Raised by the training loop's safe point after the emergency
    checkpoint landed: the process should deregister and exit cleanly,
    and a restart resumes from `checkpoint_dir`.  `exit_code` carries
    the launcher protocol (ELASTIC_EXIT_CODE when an elastic manager
    wants a relaunch, 0 for a plain clean exit)."""

    def __init__(self, reason, checkpoint_dir=None, step=None, exit_code=0):
        msg = f"training preempted ({reason})"
        if checkpoint_dir is not None:
            msg += f"; resumable checkpoint at {checkpoint_dir}"
        super().__init__(msg)
        self.reason = reason
        self.checkpoint_dir = checkpoint_dir
        self.step = step
        self.exit_code = int(exit_code)


class PreemptionGuard:
    # what TrainingPreempted.exit_code should carry when THIS guard
    # trips a training loop; ElasticManager.attach_preemption_guard
    # sets it to ELASTIC_EXIT_CODE (relaunch-me protocol)
    exit_code = 0

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 maintenance_hook=None, maintenance_interval=5.0,
                 clock=time.monotonic):
        self.signals = tuple(signals)
        self.maintenance_hook = maintenance_hook
        self.maintenance_interval = float(maintenance_interval)
        self.clock = clock
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._reason = None
        self._callbacks = []
        self._prev_handlers = {}
        self._last_poll = None
        self._pending_signal = None  # written ONLY in signal context
        self._pending_lock = threading.Lock()

    # --- signal wiring -------------------------------------------------------
    def install(self):
        """Install the trip handler for `signals` (main thread only —
        CPython restriction), remembering the previous handlers.
        Idempotent; returns self for `guard = PreemptionGuard().install()`."""
        for sig in self.signals:
            if sig in self._prev_handlers:
                continue
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self):
        """Restore the handlers `install()` replaced.  Idempotent."""
        while self._prev_handlers:
            sig, prev = self._prev_handlers.popitem()
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # pt-lint: ok[PT005]
                pass  # non-main thread / handler gone at teardown —
                # restoring is best-effort, never worth crashing exit

    def __enter__(self):
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        self.uninstall()
        return False

    def _handler(self, signum, frame):
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        # SIGNAL CONTEXT: CPython runs this on the main thread, which
        # may be interrupted while HOLDING the metrics/flight/admission
        # locks the trip path acquires — taking any of them here can
        # deadlock the process through its whole grace window.  Only a
        # GIL-atomic attribute write happens here; the actual trip
        # (counters, flight event, callbacks) runs on a helper thread,
        # with check()/preempted as the polling fallback.
        self._pending_signal = name  # pt-lint: ok[PT101] (signal
        # context MUST stay lock-free — GIL-atomic write; consumers
        # read-and-clear under _pending_lock in _process_pending)
        try:
            threading.Thread(target=self._process_pending,
                             name="preemption-trip",
                             daemon=True).start()
        except RuntimeError:  # pt-lint: ok[PT005]
            pass  # interpreter teardown / thread limit: the next
            # check()/preempted poll processes the pending signal

    def _process_pending(self):
        """Turn a handler-recorded signal into a full trip, OUTSIDE
        signal context (helper thread or a check()/preempted poll)."""
        with self._pending_lock:
            name, self._pending_signal = self._pending_signal, None
        if name is None:
            return
        try:
            from ..observability import metrics as _metrics

            _metrics.inc("preemption.signals", signal=name)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: counting
            # must never mask the trip itself)
        self.trip(f"signal:{name}")

    # --- trip / poll ---------------------------------------------------------
    def trip(self, reason):
        """Flip the guard (idempotent; first reason wins) and fire the
        registered callbacks exactly once.  Callbacks run in the
        tripping thread and are individually guarded — one failing must
        not starve the rest of their shutdown notice."""
        with self._lock:
            if self._reason is not None:
                return
            reason = self._reason = str(reason)
            callbacks = list(self._callbacks)
        self._event.set()
        try:
            from ..observability import flight as _flight

            _flight.record("preemption.tripped", reason=reason)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard, as above)
        for cb in callbacks:
            self._run_callback(cb, reason)

    def _run_callback(self, cb, reason):
        try:
            cb(reason)
        except Exception as e:
            try:
                from ..observability import flight as _flight
                from ..observability import metrics as _metrics

                _metrics.inc("preemption.callback_errors")
                _flight.record("preemption.callback_error",
                               callback=getattr(cb, "__name__", repr(cb)),
                               error=f"{type(e).__name__}: {e}")
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard, as above)

    def on_preempt(self, cb):
        """Register `cb(reason)` to run when the guard trips.  A
        callback registered after the trip runs immediately — late
        subscribers (a server started during shutdown) still drain."""
        with self._lock:
            reason = self._reason
            if reason is None:
                self._callbacks.append(cb)
        if reason is not None:
            self._run_callback(cb, reason)
        return cb

    @property
    def preempted(self):
        if self._pending_signal is not None:  # pt-lint: ok[PT102]
            # (lock-free probe; _process_pending re-checks under lock)
            self._process_pending()  # helper thread lost the race/died
        return self._event.is_set()

    @property
    def reason(self):
        with self._lock:
            return self._reason

    def check(self):
        """Pollable safe-point probe: polls the maintenance hook (rate
        limited) and returns whether the guard has tripped.  This is
        what `DistributedTrainStep` calls between dispatches."""
        if self._pending_signal is not None:  # pt-lint: ok[PT102]
            # (lock-free probe; _process_pending re-checks under lock)
            self._process_pending()
        if not self._event.is_set() and self.maintenance_hook is not None:
            now = self.clock()
            if self._last_poll is None or \
                    now - self._last_poll >= self.maintenance_interval:
                self._last_poll = now
                try:
                    pending = self.maintenance_hook()
                except Exception as e:
                    pending = None
                    try:
                        from ..observability import flight as _flight

                        _flight.record("preemption.maintenance_poll_error",
                                       error=f"{type(e).__name__}: {e}")
                    except Exception:  # pt-lint: ok[PT005]
                        pass           # (observability fan-out guard)
                if pending:
                    try:
                        from ..observability import metrics as _metrics

                        _metrics.inc("preemption.maintenance_events")
                    except Exception:  # pt-lint: ok[PT005]
                        pass           # (observability fan-out guard)
                    self.trip(f"maintenance:{pending}")
        return self._event.is_set()

    def wait(self, timeout=None):
        """Block until the guard trips (serving main loops park here).
        Polls the signal-pending flag so a trip still lands even when
        the handler's helper thread could not spawn."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            if self._pending_signal is not None:  # pt-lint: ok[PT102]
                # (lock-free probe; re-checked under _pending_lock)
                self._process_pending()
            if deadline is None:
                remaining = 0.1
            else:
                remaining = min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    return self._event.is_set()
            if self._event.wait(remaining):
                return True
