"""Deterministic, seeded fault injection with named fault points.

Pod-scale training only works when preemption and failure are routine,
which means every recovery path must be *testable* — this harness makes
each failure mode deterministically injectable so CI exercises the same
reflexes production needs (PAPERS.md: MLPerf TPU-v3 pod scaling;
EQuARX collective faults).

Named fault points (instrumented call sites `fire()` these):

  checkpoint.write   distributed/checkpoint/api.py  per shard file write
  collective.call    distributed/collective.py      eager collective exec
  dataloader.batch   io/dataloader.py               per yielded batch
  jit.compile        jit/api.py                     to_static trace/compile build
  train.step         distributed/train_step.py      per host dispatch
  serving.request    inference/serving.py           per predict call
  store.op           distributed/fleet/elastic.py   heartbeat store traffic
  router.forward     inference/router.py            per forward attempt
  router.stream_read inference/router.py            per streamed /generate
                     line read off a replica (an injection here severs
                     the stream mid-flight — the deterministic stand-in
                     for a replica dying with tokens delivered)
  router.resume_verify inference/router.py          per resume
                     first-token divergence check (an injection forces
                     the mismatch branch — the loud `interrupted`
                     fallback, never a wrong token)
  replica.crash      inference/fleet.py             replica main loop tick
                     (kind="error" → the replica exits non-zero; any
                     other kind → immediate os._exit, a simulated
                     kill -9)

Activation is programmatic (`inject(...)` — usually as a context
manager in tests) or via env:

  PADDLE_TPU_FAULTS="collective.call,p=0.3,times=2;train.step,at=3,kind=nan"
  PADDLE_TPU_FAULT_SEED=1234

Each rule is evaluated deterministically: probability draws come from a
`random.Random` seeded per rule (global seed + point name + rule index),
and count triggers (`at`, `every`, `after`) key on the per-point call
counter — the same seed and call sequence always injects the same
faults.  Every injection lands on the PR-1 flight recorder (and hence
the PR-2 trace timeline) and bumps `resilience.faults{point=...}`.
"""
from __future__ import annotations

import os
import threading
import zlib

__all__ = [
    "FAULT_POINTS", "InjectedFault", "FaultRule", "FaultAction",
    "inject", "fire", "clear", "active", "call_count", "reset_counters",
    "configure_from_env", "corrupt_file",
]

FAULT_POINTS = (
    "checkpoint.write", "collective.call", "dataloader.batch",
    "jit.compile", "train.step", "serving.request", "store.op",
    "router.forward", "router.stream_read", "router.resume_verify",
    "replica.crash",
)

_ENV_SPEC = "PADDLE_TPU_FAULTS"
_ENV_SEED = "PADDLE_TPU_FAULT_SEED"


class InjectedFault(RuntimeError):
    """The error a kind="error" (default) fault raises at its fault
    point.  Carries the point and the payload so recovery code and
    tests can assert on exactly which injection fired."""

    def __init__(self, point, kind="error", call=None, **payload):
        self.point = point
        self.kind = kind
        self.call = call
        self.payload = payload
        detail = f" call={call}" if call is not None else ""
        super().__init__(f"injected fault at {point!r} (kind={kind}{detail})")


class FaultAction:
    """What a non-raising fault asks the site to do: `kind` names the
    behavior the instrumented site implements (e.g. "torn" / "corrupt"
    for checkpoint.write, "nan" for train.step)."""

    __slots__ = ("point", "kind", "call", "payload")

    def __init__(self, point, kind, call, payload):
        self.point = point
        self.kind = kind
        self.call = call
        self.payload = dict(payload)

    def __repr__(self):
        return f"<FaultAction {self.point} kind={self.kind} call={self.call}>"


class FaultRule:
    """One armed injection at one point.

    Triggers (combinable; all that are set must agree):
      p      probability per call (seeded draw)
      at     fire exactly on the Nth call to the point (1-based)
      every  fire on every Nth call
      after  only calls strictly beyond N are eligible
      times  stop after firing N times (default: p/every unlimited,
             `at` implies times=1)
    """

    def __init__(self, point, kind="error", p=None, at=None, every=None,
                 after=0, times=None, seed=None, index=0, **payload):
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (known: {FAULT_POINTS})")
        import random

        self.point = point
        self.kind = str(kind)
        self.p = None if p is None else float(p)
        self.at = None if at is None else int(at)
        self.every = None if every is None else int(every)
        self.after = int(after)
        if times is None:
            times = 1 if self.at is not None else None
        self.times = None if times is None else int(times)
        self.fired = 0
        self.payload = payload
        base = int(seed if seed is not None
                   else os.environ.get(_ENV_SEED, "0"))
        # per-rule deterministic stream: global seed x point x rule index
        self._rng = random.Random(
            (base * 1000003) ^ zlib.crc32(point.encode()) ^ (int(index) << 17))

    def should_fire(self, call_n):
        if self.times is not None and self.fired >= self.times:
            return False
        if call_n <= self.after:
            return False
        if self.at is not None and call_n != self.at:
            return False
        if self.every is not None and call_n % self.every != 0:
            return False
        if self.p is not None and self._rng.random() >= self.p:
            return False
        return True


class _FaultState:
    def __init__(self):
        self.lock = threading.Lock()
        self.rules: list = []
        self.counts: dict = {}
        self.injected: list = []  # (point, kind, call) log for tests


_state = _FaultState()
_env_loaded = False


def _parse_env_spec(spec):
    """`point,k=v,k=v;point2,...` → list of FaultRule."""
    rules = []
    for i, part in enumerate(filter(None, (s.strip()
                                           for s in spec.split(";")))):
        fields = [f.strip() for f in part.split(",") if f.strip()]
        point, kwargs = fields[0], {}
        for f in fields[1:]:
            k, _, v = f.partition("=")
            kwargs[k.strip()] = v.strip()
        for k in ("p",):
            if k in kwargs:
                kwargs[k] = float(kwargs[k])
        for k in ("at", "every", "after", "times", "seed"):
            if k in kwargs:
                kwargs[k] = int(kwargs[k])
        rules.append(FaultRule(point, index=i, **kwargs))
    return rules


def configure_from_env(force=False):
    """Arm rules from $PADDLE_TPU_FAULTS (idempotent; `force` re-reads)."""
    global _env_loaded
    if _env_loaded and not force:
        return
    _env_loaded = True
    spec = os.environ.get(_ENV_SPEC, "")
    if spec:
        with _state.lock:
            _state.rules.extend(_parse_env_spec(spec))


class _Injection:
    """Context-manager handle for one armed rule (tests: `with
    faults.inject("collective.call", times=2): ...`).  Usable without
    `with` for process-lifetime arming."""

    def __init__(self, rule):
        self.rule = rule

    def __enter__(self):
        return self.rule

    def __exit__(self, *exc):
        with _state.lock:
            if self.rule in _state.rules:
                _state.rules.remove(self.rule)
        return False


def inject(point, kind="error", **kwargs):
    """Arm one fault rule at `point`.  Returns a context manager that
    disarms on exit (the rule object is its `as` target)."""
    with _state.lock:
        rule = FaultRule(point, kind=kind, index=len(_state.rules), **kwargs)
        _state.rules.append(rule)
    return _Injection(rule)


def clear():
    """Disarm everything and forget call counters."""
    with _state.lock:
        _state.rules.clear()
        _state.counts.clear()
        _state.injected.clear()


def active():
    """Snapshot of armed rules (shared objects — read-only use)."""
    with _state.lock:
        return list(_state.rules)


def call_count(point):
    with _state.lock:
        return _state.counts.get(point, 0)


def reset_counters():
    with _state.lock:
        _state.counts.clear()


def injected_log():
    """(point, kind, call) tuples of every injection so far."""
    with _state.lock:
        return list(_state.injected)


def fire(point, **ctx):
    """Evaluate the armed rules at a fault point.

    Returns None (the overwhelmingly common case — one lock'd counter
    bump when any rule is armed, a plain pass-through when none are),
    raises `InjectedFault` for kind="error" rules, or returns a
    `FaultAction` the call site interprets for special kinds ("torn",
    "corrupt", "nan", "stall", ...).
    """
    configure_from_env()
    # lock-free fast path: with no rules armed (production), fire() is
    # a list-emptiness check — no shared mutex on eager collectives,
    # dataloader batches, or concurrent serving requests.  The benign
    # race (a rule armed concurrently) only delays it by one call.
    if not _state.rules:
        return None
    with _state.lock:
        if not _state.rules:
            return None
        n = _state.counts.get(point, 0) + 1
        _state.counts[point] = n
        hit = None
        for rule in _state.rules:
            if rule.point == point and rule.should_fire(n):
                rule.fired += 1
                hit = rule
                break
        if hit is not None:
            _state.injected.append((point, hit.kind, n))
    if hit is None:
        return None
    _record_injection(point, hit.kind, n, ctx)
    payload = dict(hit.payload)
    payload.update(ctx)
    if hit.kind == "error":
        raise InjectedFault(point, kind="error", call=n, **payload)
    return FaultAction(point, hit.kind, n, payload)


def _record_injection(point, kind, call_n, ctx):
    """Every injection is observable: a flight-ring event (which doubles
    as a trace instant) + a metrics counter.  Telemetry failures must
    never change fault semantics."""
    try:
        from ..observability import flight as _flight
        from ..observability import metrics as _metrics

        _metrics.inc("resilience.faults", point=point)
        safe_ctx = {k: v for k, v in ctx.items()
                    if k not in ("kind", "point", "call")
                    and isinstance(v, (str, int, float, bool, list, tuple))}
        # NB: the payload key is fault_kind — `kind` is record()'s own
        # event-name parameter
        _flight.record("resilience.fault_injected", point=point,
                       fault_kind=kind, call=call_n, **safe_ctx)
    except Exception:  # pt-lint: ok[PT005] (observability fan-out
        pass           # guard: injection must not depend on telemetry)


def corrupt_file(path, seed=0, nbytes=1):
    """Deterministically flip `nbytes` bytes of the file at `path`
    (bit-rot simulation for CRC tests).  Returns the flipped offsets."""
    import random

    size = os.path.getsize(path)
    if size == 0:
        return []
    rng = random.Random(
        (int(seed) * 1000003) ^ zlib.crc32(os.path.basename(path).encode()))
    offsets = sorted(rng.randrange(size) for _ in range(max(1, int(nbytes))))
    with open(path, "r+b") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    return offsets
