"""paddle_tpu.resilience — framework-wide fault tolerance (docs/RESILIENCE.md).

PR 1-2 built the eyes (metrics, flight recorder, trace timeline); this
package is the reflexes, and makes every failure mode deterministically
injectable so the reflexes are testable in CI:

  * `faults`   — seeded fault-injection harness with named fault points
    (checkpoint.write, collective.call, dataloader.batch, jit.compile,
    train.step, serving.request, store.op); every injection is a flight
    event + `resilience.faults{point}` counter.
  * `retry`    — RetryPolicy (exponential backoff + seeded jitter,
    deadlines, circuit breaker) wrapped around eager collectives, the
    elastic manager's TCPStore heartbeats, and serving requests.
  * `guards`   — in-step NaN/Inf guard fused into the compiled train
    step (finiteness reduction, on-device skip via `where`) + host-side
    warn → skip → rollback escalation that composes with amp's
    GradScaler and rolls back through hardened checkpoints.
  * `watchdog` — heartbeat hang watchdog fed by StepTimer; dumps the
    flight ring + Perfetto trace on stall before raising.
  * `overload` — serving admission control: bounded wait queue,
    concurrency limit with an AIMD adaptive ceiling fed by observed
    latency, deadline-aware load shedding, graceful drain.
  * `preemption` — SIGTERM/SIGINT + maintenance-event guard turning
    preemption into a cooperative shutdown: training checkpoints at
    the next safe point and exits resumable; serving drains.

Recovery state (what rollback restores through) lives in the hardened
distributed checkpoint: atomic tmp+fsync+rename saves, per-shard CRC32s
verified on load, keep-last-K rotation with a `latest` pointer
(`distributed.checkpoint.CheckpointManager`).
"""
from __future__ import annotations

from . import faults, guards, overload, preemption, retry, watchdog  # noqa: F401
from .faults import InjectedFault, inject  # noqa: F401
from .guards import StepGuard  # noqa: F401
from .overload import AdmissionController, ShedError  # noqa: F401
from .preemption import PreemptionGuard, TrainingPreempted  # noqa: F401
from .retry import (  # noqa: F401
    CircuitBreaker, CircuitOpenError, DeadlineExceeded, RetryPolicy,
)
from .watchdog import Watchdog, WatchdogStall  # noqa: F401

__all__ = [
    "faults", "retry", "guards", "watchdog", "overload", "preemption",
    "InjectedFault", "inject", "StepGuard", "RetryPolicy",
    "CircuitBreaker", "CircuitOpenError", "DeadlineExceeded",
    "Watchdog", "WatchdogStall", "AdmissionController", "ShedError",
    "PreemptionGuard", "TrainingPreempted",
]
