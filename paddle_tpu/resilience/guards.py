"""In-step NaN/Inf guards: finiteness reductions that compile into the
train step, plus the host-side warn → skip → rollback escalation.

Two layers, split along the host/device boundary:

* device (traced, zero host sync): `tree_finite(loss, grads)` reduces
  loss + every grad leaf to ONE boolean; `tree_select(ok, new, old)`
  keeps the previous state when the step went bad.  With `ok` True the
  selected leaves are the new values bit-for-bit — a guarded step with
  no faults matches the unguarded trajectory exactly (acceptance
  criterion; `jnp.where` selects, it does not recompute).
* host (`StepGuard`): consumes the per-step ok flag (one scalar
  transfer), counts CONSECUTIVE bad steps and escalates:
      1st bad        → "warn"  (flight event, counter — state already
                                 kept by the in-program select)
      2..K-1th bad   → "skip"
      Kth bad        → "rollback" (invokes the registered callback —
                                 typically CheckpointManager.restore —
                                 and resets the streak)
  Composes with `amp.GradScaler`: scaler-reported overflow steps are
  EXPECTED while dynamic loss scaling searches for the right scale, so
  they count toward the streak only after the scale has bottomed out
  (scaler at min scale and still overflowing = genuinely sick run).
"""
from __future__ import annotations

import threading

__all__ = ["tree_finite", "tree_select", "StepGuard", "RollbackError"]


class RollbackError(RuntimeError):
    """Escalation reached rollback but no rollback callback is
    registered (or the callback itself failed)."""


def tree_finite(loss, grads=None):
    """One scalar bool: loss AND every floating grad leaf all-finite.
    Traced — lowers to cheap reductions fused into the step program."""
    import jax
    import jax.numpy as jnp

    ok = jnp.all(jnp.isfinite(loss))
    if grads is not None:
        for g in jax.tree_util.tree_leaves(grads):
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


def tree_select(ok, new_tree, old_tree):
    """Per-leaf `where(ok, new, old)` across a pytree (skip-step on
    device: bad step keeps the old state without a host round-trip)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


class StepGuard:
    """Host-side escalation ladder over per-step finiteness flags.

    observe(ok) → "ok" | "warn" | "skip" | "rollback".  Thread-safe;
    wire a rollback with `on_rollback=` (callable, no args) or
    `set_rollback(fn)` — DistributedTrainStep does this automatically
    when it owns a CheckpointManager.
    """

    def __init__(self, max_consecutive_bad=3, on_rollback=None,
                 raise_without_rollback=True, name="train"):
        self.max_consecutive_bad = max(1, int(max_consecutive_bad))
        self.on_rollback = on_rollback
        self.raise_without_rollback = bool(raise_without_rollback)
        self.name = str(name)
        self._lock = threading.Lock()
        self.consecutive_bad = 0
        self.total_bad = 0
        self.total_steps = 0
        self.rollbacks = 0

    def set_rollback(self, fn):
        self.on_rollback = fn

    def observe(self, ok, source="guard"):
        """Feed one step's finiteness verdict; returns the action taken.

        source="amp": a GradScaler-reported overflow.  While the scaler
        still has room to decrease the loss scale this is part of normal
        dynamic-scaling operation — recorded (counter + flight) but not
        escalated.  Pass source="amp_floor" (scaler at minimum scale)
        to count it against the streak like a guard-detected bad step.
        """
        ok = bool(ok)
        with self._lock:
            self.total_steps += 1
            if ok:
                self.consecutive_bad = 0
                return "ok"
            self.total_bad += 1
            if source == "amp":
                self._emit("skip", source)
                return "skip"
            self.consecutive_bad += 1
            streak = self.consecutive_bad
            if streak >= self.max_consecutive_bad:
                self.consecutive_bad = 0
                self.rollbacks += 1
                action = "rollback"
            elif streak == 1:
                action = "warn"
            else:
                action = "skip"
        self._emit(action, source, streak=streak)
        if action == "rollback":
            self._rollback(streak)
        return action

    def _rollback(self, streak):
        cb = self.on_rollback
        if cb is None:
            if self.raise_without_rollback:
                raise RollbackError(
                    f"guard {self.name!r}: {streak} consecutive non-finite "
                    f"steps and no rollback target registered")
            return
        try:
            cb()
        except Exception as e:
            raise RollbackError(
                f"guard {self.name!r}: rollback callback failed: {e}") from e

    def _emit(self, action, source, streak=None):
        try:
            from ..observability import flight as _flight
            from ..observability import metrics as _metrics

            if action in ("warn", "skip"):
                _metrics.inc("resilience.skipped_steps", source=source)
            elif action == "rollback":
                _metrics.inc("resilience.rollbacks")
            extra = {} if streak is None else {"streak": streak}
            _flight.record(f"resilience.guard_{action}", guard=self.name,
                           source=source, **extra)
        except Exception:  # pt-lint: ok[PT005] (observability fan-out
            pass           # guard: guarding must not depend on telemetry)

    def state_dict(self):
        with self._lock:
            return {"consecutive_bad": self.consecutive_bad,
                    "total_bad": self.total_bad,
                    "total_steps": self.total_steps,
                    "rollbacks": self.rollbacks}
