"""Serving admission control: bounded queue + concurrency limit +
deadline-aware load shedding (docs/RESILIENCE.md).

The front door of `inference/serving.py`: every request must pass an
`AdmissionController` BEFORE it can touch the predictor lock.  Without
one, overload has exactly one failure mode — requests pile up
unboundedly on the lock, all of them eventually time out together, and
the clients retry in a herd that keeps the server saturated forever.
With one, the server does bounded work and says "no" cheaply:

  * **concurrency limit** (`max_inflight`): at most this many requests
    run the predictor concurrently (the device executes one program at
    a time; extra concurrency only buys queue depth inside XLA).
  * **bounded wait queue** (`queue_depth`): at most this many requests
    wait for a slot; the next one is shed immediately (`queue_full`).
  * **deadline-aware shedding**: a request whose estimated completion
    time (queue ahead of it x observed latency / limit + its own
    service) already overruns its deadline is shed at the door instead
    of timing out after consuming a slot (`deadline`).
  * **AIMD adaptive limit**: when a `latency_target` is set, the
    observed per-request latency EWMA drives the live limit — latency
    over target multiplies the limit down (fast backoff under
    overload), a window of on-target completions adds 1 back (slow
    recovery), classic TCP-style AIMD bounded to
    [`min_limit`, `max_inflight`].
  * **draining**: `begin_drain()` flips the controller into shutdown
    mode — new and queued requests are shed (`draining`, HTTP 503),
    in-flight ones finish; `drain(timeout)` blocks until they have.
  * **QoS classes** (ISSUE 18): admission is class-aware.  The wait
    queue is partitioned by nested weighted shares (batch may occupy
    at most its share, free+batch theirs, paid the whole depth), the
    dequeue order is strict priority (paid > free > batch, FIFO within
    a class) with an aging knob that promotes a starved waiter one
    rank per `qos_age_s` so batch still eventually runs, a full queue
    sheds the lowest-class youngest waiter to make room for a
    higher-class arrival (shed lowest FIRST — never the paid request),
    and `Retry-After` scales by class so free/batch back off honestly
    longer under the same pressure estimate.

Every shed increments `resilience.shed_requests{reason=...}` (and
`qos.shed{class=...}`) and lands a flight instant; `serving.inflight` /
`serving.queue_depth` / `serving.admission_limit` gauges track the live
state.  Clock is injectable — tests run the whole machine without
wall-clock waits.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_MAX_INFLIGHT    concurrency limit        (default 4)
  PADDLE_TPU_QUEUE_DEPTH     bounded queue length     (default 16)
  PADDLE_TPU_QUEUE_TIMEOUT   max queue wait, seconds  (default 10)
  PADDLE_TPU_LATENCY_TARGET  AIMD latency target, seconds (default off)
  PADDLE_TPU_QOS_AGE_S       starvation aging: +1 rank per this many
                             queued seconds (default 30; 0 disables)
"""
from __future__ import annotations

import math
import os
import threading
import time

from ..inference import qos as _qos

__all__ = ["AdmissionController", "ShedError", "AdmissionTicket"]

_MAX_RANK = max(_qos.class_rank(c) for c in _qos.CLASSES)


def _env_num(var, default, cast):
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError:
        raise ValueError(f"{var} must parse as {cast.__name__}, "
                         f"got {raw!r}") from None


class ShedError(RuntimeError):
    """A request was refused at admission.  `reason` is one of
    `queue_full` / `queue_timeout` / `deadline` / `draining` (plus
    `no_replicas` at the fleet router's edge); `retry_after` is the
    server's estimate
    (seconds) of when retrying could succeed — serving surfaces it as
    an HTTP `Retry-After` header.  Overload sheds map to 429 (back off
    and retry), draining / no_replicas to 503 (this instance cannot
    serve you — retry elsewhere / later)."""

    def __init__(self, reason, retry_after=1.0, detail=""):
        super().__init__(
            f"request shed ({reason})" + (f": {detail}" if detail else ""))
        self.reason = str(reason)
        self.retry_after = max(0.0, float(retry_after))

    @property
    def http_status(self):
        return 503 if self.reason in ("draining", "no_replicas") else 429


class AdmissionTicket:
    """One admitted request's slot.  Context-manager form releases on
    exit with ok = no-exception; `release()` is idempotent.
    `queue_wait` is the seconds this request spent waiting for a slot —
    the "queue" phase of the request-trace breakdown (ISSUE 7)."""

    __slots__ = ("_controller", "_start", "_released", "queue_wait")

    def __init__(self, controller, start, queue_wait=0.0):
        self._controller = controller
        self._start = start
        self._released = False
        self.queue_wait = float(queue_wait)

    def release(self, ok=True):
        if self._released:
            return
        self._released = True
        latency = self._controller.clock() - self._start
        self._controller._release(ok=ok, latency=latency)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release(ok=exc_type is None)
        return False


class _Waiter:
    """One queued request's QoS bookkeeping: its class/rank, when it
    enqueued (FIFO within a class + the aging promotion both read it),
    and whether a higher-class arrival displaced it out of a full
    queue (it sheds itself on wakeup)."""

    __slots__ = ("cls", "rank", "enq", "displaced")

    def __init__(self, cls, rank, enq):
        self.cls = cls
        self.rank = rank
        self.enq = enq
        self.displaced = False


class AdmissionController:
    def __init__(self, max_inflight=None, queue_depth=None,
                 queue_timeout=None, latency_target=None, min_limit=1,
                 ewma_alpha=0.3, decrease_factor=0.7, name="serving",
                 clock=time.monotonic, qos_age_s=None):
        if max_inflight is None:
            max_inflight = _env_num("PADDLE_TPU_MAX_INFLIGHT", 4, int)
        if queue_depth is None:
            queue_depth = _env_num("PADDLE_TPU_QUEUE_DEPTH", 16, int)
        if queue_timeout is None:
            queue_timeout = _env_num("PADDLE_TPU_QUEUE_TIMEOUT", 10.0, float)
        if latency_target is None:
            latency_target = _env_num("PADDLE_TPU_LATENCY_TARGET", 0.0,
                                      float) or None
        if qos_age_s is None:
            qos_age_s = _env_num("PADDLE_TPU_QOS_AGE_S", 30.0, float)
        self.max_inflight = max(1, int(max_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self.queue_timeout = float(queue_timeout)
        self.latency_target = latency_target
        self.min_limit = max(1, min(int(min_limit), self.max_inflight))
        self.ewma_alpha = float(ewma_alpha)
        self.decrease_factor = float(decrease_factor)
        self.name = str(name)
        self.clock = clock
        self.qos_age_s = max(0.0, float(qos_age_s))
        self._cv = threading.Condition(threading.Lock())
        self._limit = self.max_inflight
        self._inflight = 0
        self._queued = 0
        self._waiters = []     # live _Waiter records (insertion order)
        self._draining = False
        self._ewma = None      # EWMA of observed request latency (s)
        self._good = 0         # on-target completions since last bump
        self._shed = {"queue_full": 0, "queue_timeout": 0,
                      "deadline": 0, "draining": 0}
        self._shed_by_class = {c: 0 for c in _qos.CLASSES}
        self._completed = 0
        self._failed = 0
        self._publish_gauges()

    # --- introspection ------------------------------------------------------
    @property
    def draining(self):
        with self._cv:
            return self._draining

    @property
    def limit(self):  # pt-lint: ok[PT102] (monitoring read: a stale
        # limit is a fine answer to "what is the limit right now")
        """The LIVE concurrency limit (AIMD moves it within
        [min_limit, max_inflight]; fixed at max_inflight otherwise)."""
        return self._limit

    def set_capacity(self, max_inflight):
        """Re-size the concurrency limit at runtime — the fleet router
        uses this to track live backend capacity (replicas ejected or
        re-admitted change how much work the edge may admit).  Without
        a `latency_target` the live limit follows the new capacity
        exactly; with AIMD active, the adjusted limit is clamped into
        the new [min_limit, max_inflight] band but otherwise keeps its
        learned value.  Waiters are woken: a capacity increase can
        admit a queued request immediately."""
        with self._cv:
            self.max_inflight = max(1, int(max_inflight))
            # keep the AIMD band non-empty: a shrink below min_limit
            # drags min_limit down with it (mirror of __init__), or
            # the clamp below would hold _limit ABOVE the new capacity
            self.min_limit = min(self.min_limit, self.max_inflight)
            if self.latency_target is None:
                self._limit = self.max_inflight
            else:
                self._limit = max(self.min_limit,
                                  min(self._limit, self.max_inflight))
            self._publish_gauges()
            self._cv.notify_all()

    def stats(self):
        with self._cv:
            queued_by_class = {c: 0 for c in _qos.CLASSES}
            for w in self._waiters:
                if not w.displaced:
                    queued_by_class[w.cls] += 1
            return {
                "inflight": self._inflight,
                "queued": self._queued,
                "limit": self._limit,
                "max_inflight": self.max_inflight,
                "queue_depth": self.queue_depth,
                "draining": self._draining,
                "ewma_latency": self._ewma,
                "completed": self._completed,
                "failed": self._failed,
                "shed": dict(self._shed),
                "queued_by_class": queued_by_class,
                "shed_by_class": dict(self._shed_by_class),
            }

    # --- QoS queue policy (callers hold _cv) --------------------------------
    def _class_cap_locked(self, rank):  # pt-lint: ok[PT102] (callers hold _cv)
        """Nested weighted partition cap for classes at-or-below
        `rank`: batch may occupy at most its weighted share of the
        queue, free+batch theirs, and the top class the whole depth —
        so a flood of low-class arrivals can never camp the queue a
        paid request needs."""
        total = sum(_qos.class_weight(c) for c in _qos.CLASSES)
        share = sum(_qos.class_weight(c) for c in _qos.CLASSES
                    if _qos.class_rank(c) <= rank)
        if share >= total:
            return self.queue_depth
        return min(self.queue_depth,
                   max(1, math.ceil(self.queue_depth * share / total)))

    def _effective_rank_locked(self, w, now):  # pt-lint: ok[PT102] (callers hold _cv)
        """Rank after aging: one rank per `qos_age_s` queued seconds,
        capped at the top — bounds starvation (a batch waiter
        eventually outranks a steady paid stream and runs)."""
        if self.qos_age_s <= 0:
            return w.rank
        return min(_MAX_RANK,
                   w.rank + int((now - w.enq) / self.qos_age_s))

    def _head_waiter_locked(self, now):  # pt-lint: ok[PT102] (callers hold _cv)
        """Strict-priority dequeue order: highest effective rank wins,
        FIFO within a rank."""
        best, best_key = None, None
        for w in self._waiters:
            if w.displaced:
                continue
            key = (self._effective_rank_locked(w, now), -w.enq)
            if best is None or key > best_key:
                best, best_key = w, key
        return best

    def _retry_after_locked(self, cls, base=None):  # pt-lint: ok[PT102] (callers hold _cv)
        """Class-aware backoff: the same pressure estimate, scaled so
        free/batch clients honestly wait longer before retrying than
        the paid tier they would otherwise race."""
        base = self._estimate_wait() if base is None else base
        return base * _qos.retry_after_factor(cls)

    # --- admission ----------------------------------------------------------
    def admit(self, deadline=None, priority_class=None):
        """Admit one request (blocking while the queue drains ahead of
        it) and return an `AdmissionTicket`, or raise `ShedError`.
        `deadline` is an absolute `clock()` instant the caller must
        finish by; admission refuses work it estimates cannot finish in
        time.  `priority_class` orders everything: queue partition,
        dequeue order, who gets displaced from a full queue, and the
        `Retry-After` a shed carries."""
        cls = _qos.normalize_class(priority_class) or _qos.DEFAULT_CLASS
        rank = _qos.class_rank(cls)
        with self._cv:
            if self._draining:
                self._shed_locked("draining", self._drain_retry_after(),
                                  cls=cls)
            # queue_full only applies to requests that would actually
            # have to queue — a free slot admits regardless of depth 0
            if self._inflight >= self._limit:
                cap = self._class_cap_locked(rank)
                while True:
                    active = [w for w in self._waiters if not w.displaced]
                    at_or_below = sum(1 for w in active if w.rank <= rank)
                    if len(active) < self.queue_depth and \
                            at_or_below < cap:
                        break
                    # full for this class: shed the lowest-class
                    # YOUNGEST waiter that this request outranks —
                    # lowest class degrades first, oldest work survives
                    victim = min(
                        (w for w in active if w.rank < rank),
                        key=lambda w: (w.rank, -w.enq), default=None)
                    if victim is None:
                        self._shed_locked(
                            "queue_full", self._retry_after_locked(cls),
                            cls=cls)
                    victim.displaced = True
                    self._cv.notify_all()
            est = self._estimate_wait()
            if deadline is not None and self.clock() + est > deadline:
                self._shed_locked(
                    "deadline", self._retry_after_locked(cls, est),
                    cls=cls,
                    detail=f"estimated completion {est:.3f}s past deadline")
            self._queued += 1
            waiter = _Waiter(cls, rank, self.clock())
            self._waiters.append(waiter)
            self._publish_gauges()
            wait_t0 = self.clock()
            qspan = None
            try:
                # queue_timeout bounds the head-of-line wait even when
                # the request's own deadline is laxer — whichever comes
                # first sheds (a 30s request deadline must not grant a
                # 30s queue camp when the operator capped waits at 1s)
                timeout_at = self.clock() + self.queue_timeout
                if deadline is not None:
                    timeout_at = min(timeout_at, deadline)
                while True:
                    if waiter.displaced:
                        self._shed_locked(
                            "queue_full", self._retry_after_locked(cls),
                            cls=cls,
                            detail="displaced by a higher-class arrival")
                    if self._draining:
                        self._shed_locked("draining",
                                          self._drain_retry_after(),
                                          cls=cls)
                    now = self.clock()
                    if self._inflight < self._limit and \
                            self._head_waiter_locked(now) is waiter:
                        break
                    remaining = timeout_at - now
                    if remaining <= 0:
                        if deadline is not None and now >= deadline:
                            # the request's own deadline was the
                            # binding bound: report the actionable
                            # reason, not a generic queue timeout
                            self._shed_locked(
                                "deadline",
                                self._retry_after_locked(cls), cls=cls,
                                detail="queue wait exhausted the deadline")
                        self._shed_locked(
                            "queue_timeout",
                            self._retry_after_locked(cls), cls=cls,
                            detail="queue wait exceeded the operator "
                                   "queue timeout")
                    if qspan is None:
                        # this request will actually wait: its queue
                        # camp is a span on the request trace (request
                        # id attached via the active RequestContext)
                        qspan = self._begin_queue_span()
                    self._cv.wait(remaining)
                self._inflight += 1
            finally:
                self._end_queue_span(qspan)
                self._queued -= 1
                try:
                    self._waiters.remove(waiter)
                except ValueError:
                    pass
                self._publish_gauges()
                # a shed waiter leaving the queue can be the drain()
                # waiter's last blocker — wake it to re-check; an
                # admitted head must also pass the baton to the next
                self._cv.notify_all()
            queue_wait = self.clock() - wait_t0
        return AdmissionTicket(self, self.clock(), queue_wait=queue_wait)

    def _release(self, ok, latency):
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            if ok:
                self._completed += 1
            else:
                self._failed += 1
            self._observe_locked(latency)
            self._publish_gauges()
            self._cv.notify_all()

    # --- load estimation / AIMD ---------------------------------------------
    def _estimate_wait(self):  # pt-lint: ok[PT102] (callers hold _cv)
        """Estimated time for a request admitted NOW to complete: the
        work ahead of it (queued + inflight) served at `limit`-way
        concurrency, plus its own service time — all at the observed
        latency EWMA.  Zero until the first completion (no evidence of
        slowness yet: admit optimistically, shed on facts)."""
        if not self._ewma:
            return 0.0
        ahead = self._queued + self._inflight
        return self._ewma * ahead / max(1, self._limit) + self._ewma

    def _drain_retry_after(self):
        # a draining instance never comes back; tell the client to try
        # another replica after roughly one service time
        return self._ewma if self._ewma else 1.0

    def _observe_locked(self, latency):  # pt-lint: ok[PT101,PT102] (callers hold _cv)
        if latency is None or latency < 0:
            return
        self._ewma = (latency if self._ewma is None else
                      (1.0 - self.ewma_alpha) * self._ewma
                      + self.ewma_alpha * latency)
        if self.latency_target is None:
            return
        if self._ewma > self.latency_target:
            new = max(self.min_limit,
                      int(math.floor(self._limit * self.decrease_factor)))
            if new < self._limit:
                self._limit = new
                self._good = 0
                self._note("resilience.admission_limit_decrease",
                           limit=new, ewma=round(self._ewma, 6))
        else:
            self._good += 1
            # additive increase once per limit-sized window of on-target
            # completions: recovery probes capacity slowly (AIMD)
            if self._good >= self._limit and self._limit < self.max_inflight:
                self._limit += 1
                self._good = 0
                self._note("resilience.admission_limit_increase",
                           limit=self._limit, ewma=round(self._ewma, 6))

    # --- drain ---------------------------------------------------------------
    def begin_drain(self):
        """Stop admitting: every new or queued request sheds with
        `draining`; in-flight requests keep their slots.  Idempotent."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
            self._publish_gauges()
            self._cv.notify_all()
        self._note("resilience.drain_begin", name=self.name)

    def drain(self, timeout=None):
        """`begin_drain()` then block until no requests are in flight or
        queued (queued ones shed themselves as they wake).  Returns True
        when fully drained, False on timeout — the caller decides
        whether a hard stop is acceptable then."""
        if timeout is None:
            timeout = _env_num("PADDLE_TPU_DRAIN_TIMEOUT", 30.0, float)
        self.begin_drain()
        deadline = self.clock() + float(timeout)
        with self._cv:
            while self._inflight > 0 or self._queued > 0:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    self._note("resilience.drain_timeout",
                               inflight=self._inflight,
                               queued=self._queued)
                    return False
                self._cv.wait(remaining)
        self._note("resilience.drain_complete", name=self.name)
        return True

    # --- observability (fan-out guarded: shedding must shed, not crash) -----
    def _begin_queue_span(self):
        """Open a `serving.queue` span carrying the active request's
        identity (request_trace contextvar) — the queue-wait phase of
        the per-request breakdown.  Guarded: a telemetry error must
        never turn a queue camp into a 500."""
        try:
            from ..observability import request_trace as _rtrace
            from ..observability import trace as _trace

            ctx = _rtrace.current()
            args = ctx.trace_args() if ctx is not None else {}
            return _trace.begin("serving.queue", cat="serving", **args)
        except Exception:  # pt-lint: ok[PT005]
            return None    # (observability fan-out guard, as below)

    @staticmethod
    def _end_queue_span(sp):
        if sp is None:
            return
        try:
            from ..observability import trace as _trace

            _trace.end(sp)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard, as below)

    def _shed_locked(self, reason, retry_after, detail="", cls=None):  # pt-lint: ok[PT102] (callers hold _cv)
        self._shed[reason] = self._shed.get(reason, 0) + 1
        if cls is not None:
            self._shed_by_class[cls] = self._shed_by_class.get(cls, 0) + 1
        try:
            from ..observability import flight as _flight
            from ..observability import metrics as _metrics

            _metrics.inc("resilience.shed_requests", reason=reason)
            if cls is not None:
                _metrics.inc("qos.shed", **{"class": cls})
            _flight.record("resilience.request_shed", reason=reason,
                           retry_after=round(float(retry_after), 3),
                           inflight=self._inflight, queued=self._queued,
                           limit=self._limit,
                           **({"cls": cls} if cls else {}))
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: a telemetry
            # error here would turn a cheap shed into a 500)
        raise ShedError(reason, retry_after=retry_after, detail=detail)

    def _publish_gauges(self):  # pt-lint: ok[PT102] (ctor + _cv holders)
        try:
            from ..observability import metrics as _metrics

            _metrics.set_gauge("serving.inflight", self._inflight)
            _metrics.set_gauge("serving.queue_depth", self._queued)
            _metrics.set_gauge("serving.admission_limit", self._limit)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard, as above)

    def _note(self, kind, **data):
        try:
            from ..observability import flight as _flight

            _flight.record(kind, **data)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard, as above)
